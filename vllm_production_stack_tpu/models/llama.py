"""Llama-family decoder (Llama 2/3, Mistral, Qwen2, TinyLlama) in pure JAX.

The reference stack never implements a model — it deploys vLLM images. The
TPU engine needs its own: a functional, scan-over-layers decoder whose
per-layer params are stacked along a leading axis so jit traces ONE layer
body (fast compiles, fixed shapes — XLA-friendly control flow instead of a
Python loop over 32 layers).

Every weight is an (in, out)-oriented matrix so the forward pass is plain
`x @ w` feeding the MXU; tensor parallelism is expressed entirely by the
PartitionSpecs in parallel/sharding.py — no collective appears in this file
(XLA/GSPMD inserts them).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..engine.config import ModelConfig
from ..ops.attention import (
    apply_rope,
    attention_with_hist,
    causal_page_mask,
    gather_pages,
    masked_attention,
    paged_attention_with_staged,
    paged_attention_xla,
    write_kv_pages,
    write_kv_pages_blockwise,
)
from ..ops.paged_attention_pallas import (
    paged_decode_attention,
    paged_decode_attention_sharded,
    paged_prefill_attention,
    paged_prefill_attention_sharded,
)


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def init_params(cfg: ModelConfig, rng: jax.Array) -> dict:
    """Random-init a stacked param tree (tests + benchmarks without weights)."""
    L = cfg.num_layers
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, it = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    dt = _dtype(cfg)
    keys = iter(jax.random.split(rng, 16))

    def w(key, *shape, scale=None):
        scale = scale if scale is not None else shape[-2] ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    if cfg.num_experts:
        # Mixtral-family sparse MoE: per-layer router + E expert SwiGLUs
        e = cfg.num_experts
        mlp = {
            "router": w(next(keys), L, h, e),
            "gate": w(next(keys), L, e, h, it),
            "up": w(next(keys), L, e, h, it),
            "down": w(next(keys), L, e, it, h),
        }
        mlp_key = "moe"
    else:
        mlp = {
            "gate": w(next(keys), L, h, it),
            "up": w(next(keys), L, h, it),
            "down": w(next(keys), L, it, h),
        }
        mlp_key = "mlp"
    params: dict[str, Any] = {
        "embed": w(next(keys), cfg.vocab_size, h, scale=0.02),
        "layers": {
            "attn": {
                "wq": w(next(keys), L, h, nh * hd),
                "wk": w(next(keys), L, h, nkv * hd),
                "wv": w(next(keys), L, h, nkv * hd),
                "wo": w(next(keys), L, nh * hd, h),
            },
            mlp_key: mlp,
            "input_norm": jnp.ones((L, h), dt),
            "post_attn_norm": jnp.ones((L, h), dt),
        },
        "final_norm": jnp.ones((h,), dt),
    }
    if cfg.attention_bias:
        params["layers"]["attn"]["bq"] = jnp.zeros((L, nh * hd), dt)
        params["layers"]["attn"]["bk"] = jnp.zeros((L, nkv * hd), dt)
        params["layers"]["attn"]["bv"] = jnp.zeros((L, nkv * hd), dt)
    if cfg.qk_norm:
        params["layers"]["attn"]["q_norm"] = jnp.ones((L, hd), dt)
        params["layers"]["attn"]["k_norm"] = jnp.ones((L, hd), dt)
    if cfg.qk_norm_flat:
        params["layers"]["attn"]["q_norm"] = jnp.ones((L, nh * hd), dt)
        params["layers"]["attn"]["k_norm"] = jnp.ones((L, nkv * hd), dt)
    if cfg.sandwich_norms or cfg.post_norms_only:
        init = jnp.zeros if cfg.rms_norm_add_one else jnp.ones
        params["layers"]["attn_out_norm"] = init((L, h), dt)
        params["layers"]["ffw_out_norm"] = init((L, h), dt)
    if cfg.post_norms_only:
        # olmo2 carries NO pre-norms at all
        del params["layers"]["input_norm"]
        del params["layers"]["post_attn_norm"]
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), h, cfg.vocab_size, scale=0.02)
    return params


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, add_one: bool = False,
    scale_f32: bool = False,
) -> jax.Array:
    """Llama convention: normalize, cast to input dtype, scale by weight.
    Gemma (add_one): weights are stored as (w - 1) and the scale by (1 + w)
    happens in float32 BEFORE the downcast. OLMo-2 (scale_f32): plain
    weights, but the multiply ALSO happens in float32 before the downcast
    (Olmo2RMSNorm) — in bf16 these orderings differ by ulps, and each
    matches its HF reference bit-for-bit."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    if add_one:
        return (normed * (1.0 + weight.astype(jnp.float32))).astype(dt)
    if scale_f32:
        return (normed * weight.astype(jnp.float32)).astype(dt)
    return normed.astype(dt) * weight


def _mm(x: jax.Array, w) -> jax.Array:
    """x @ w where w may be an int8 weight-only quantized leaf
    ({"q": int8 (…, in, out), "s": f32 (…, 1, out)},
    models/quantization.py). The HBM read is the int8 tensor; the cast and
    per-channel rescale fuse into the matmul epilogue."""
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def _activation(cfg: ModelConfig):
    if cfg.hidden_act == "silu":
        return jax.nn.silu
    if cfg.hidden_act == "gelu_tanh":  # Gemma GeGLU
        return lambda x: jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown hidden_act {cfg.hidden_act!r}")


def _embed(cfg: ModelConfig, params: dict, token_ids: jax.Array) -> jax.Array:
    x = params["embed"][token_ids].astype(_dtype(cfg))
    if cfg.scale_embeddings:  # Gemma: sqrt(h) in the embedding dtype
        x = x * jnp.asarray(cfg.hidden_size**0.5, _dtype(cfg))
    return x


def init_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype: Any | None = None
) -> tuple[jax.Array, ...]:
    """Paged pool: a TUPLE of per-layer (2, num_blocks, block_size, kvH, D)
    arrays, NOT one stacked array. Per-layer leaves let jit donation alias
    each layer's pool in place; a stacked pool updated inside a scan forces
    XLA to hold a second full-pool buffer (observed +9.8 GiB on a
    utilization-sized pool — an instant OOM)."""
    dt = jnp.dtype(dtype) if dtype is not None else _dtype(cfg)
    return tuple(
        jnp.zeros(
            (2, num_blocks, block_size, cfg.num_kv_heads, cfg.head_dim), dt
        )
        for _ in range(cfg.num_layers)
    )


def lora_module_dims(cfg: ModelConfig) -> dict[str, tuple[int, int]]:
    """(in, out) dims per PEFT target-module name. MoE models expose only the
    attention projections (per-expert MLP LoRA would need per-expert deltas —
    the MoE path never consults the adapter tree, so advertising mlp modules
    there would be a silent no-op)."""
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, it = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    dims = {
        "q_proj": (h, nh * hd),
        "k_proj": (h, nkv * hd),
        "v_proj": (h, nkv * hd),
        "o_proj": (nh * hd, h),
    }
    if not cfg.num_experts:
        dims |= {
            "gate_proj": (h, it),
            "up_proj": (h, it),
            "down_proj": (it, h),
        }
    return dims


def init_lora_params(cfg: ModelConfig, lora_cfg) -> dict:
    """Stacked adapter buffers: per target module A (n_slots, L, in, r) and
    B (n_slots, L, r, out), plus per-slot "scale" (n_slots,) = alpha/r. Slot
    0 is the reserved all-zeros base adapter, so a batch row with no adapter
    computes delta 0 through the exact same program — adapter selection is a
    per-row gather, never a recompile (SURVEY §7.3 hard part 3)."""
    n, r, L = lora_cfg.num_slots, lora_cfg.max_lora_rank, cfg.num_layers
    dt = _dtype(cfg)
    dims = lora_module_dims(cfg)
    unknown = [m for m in lora_cfg.target_modules if m not in dims]
    if unknown:
        raise ValueError(
            f"unknown LoRA target modules {unknown}; supported: "
            f"{sorted(dims)}"
        )
    tree: dict = {
        name: {
            "A": jnp.zeros((n, L, din, r), dt),
            "B": jnp.zeros((n, L, r, dout), dt),
        }
        for name, (din, dout) in dims.items()
        if name in lora_cfg.target_modules
    }
    tree["scale"] = jnp.zeros((n,), jnp.float32)
    return tree


def _lora_delta(
    x: jax.Array,  # (B, T, in) — the projection's input
    mod: dict,  # {"A": (n, in, r), "B": (n, r, out)} — this layer's slice
    idx: jax.Array,  # (B,) adapter slot per row
    scale: jax.Array,  # (B,) alpha/r per row (0 for base rows)
) -> jax.Array:
    a = mod["A"][idx]  # (B, in, r)
    b = mod["B"][idx]  # (B, r, out)
    u = jnp.einsum("bti,bir->btr", x, a)
    return jnp.einsum("btr,bro->bto", u, b) * scale[:, None, None].astype(x.dtype)


def _layer_body(
    cfg: ModelConfig,
    lp: dict,
    x: jax.Array,  # (B, T, h)
    positions: jax.Array,  # (B, T)
    attend,  # (q (B,T,nh,D), k (B,T,kvH,D), v (B,T,kvH,D)) -> (B,T,nh,D)
    lora: dict | None = None,  # layer-sliced init_lora_params tree
    lora_idx: jax.Array | None = None,  # (B,) slot per row
) -> jax.Array:
    """The Llama layer math shared by every execution mode — prefill and the
    fused decode window differ ONLY in how attention consumes/stores KV, so
    that strategy is injected as `attend` and everything else (projections,
    bias, RoPE, residuals, MLP, LoRA deltas) exists exactly once."""
    b, t, h = x.shape
    hd, nh, nkv = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads

    if lora is not None:
        lscale = lora["scale"][lora_idx]

        def proj(xin, w, name):
            out = _mm(xin, w)
            if name in lora:
                out += _lora_delta(xin, lora[name], lora_idx, lscale)
            return out
    else:

        def proj(xin, w, name):
            return _mm(xin, w)

    res = x
    if not cfg.post_norms_only:
        x = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps,
                     cfg.rms_norm_add_one)
    ap = lp["attn"]
    q = proj(x, ap["wq"], "q_proj")
    k = proj(x, ap["wk"], "k_proj")
    v = proj(x, ap["wv"], "v_proj")
    if cfg.attention_bias:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    if cfg.qk_norm_flat:
        # olmo2: RMSNorm over the whole flat projection, pre-reshape
        q = rms_norm(q, ap["q_norm"], cfg.rms_norm_eps,
                     scale_f32=cfg.norm_scale_f32)
        k = rms_norm(k, ap["k_norm"], cfg.rms_norm_eps,
                     scale_f32=cfg.norm_scale_f32)
    q = q.reshape(b, t, nh, hd)
    k = k.reshape(b, t, nkv, hd)
    if cfg.qk_norm:
        # qwen3: per-head RMSNorm on q/k BEFORE rope (HF Qwen3Attention)
        q = rms_norm(q, ap["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, ap["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, positions, cfg.rope_theta, scaling=cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, scaling=cfg.rope_scaling)
    v = v.reshape(b, t, nkv, hd)

    attn = attend(q, k, v).reshape(b, t, nh * hd)
    attn_out = proj(attn, ap["wo"], "o_proj")
    if cfg.sandwich_norms or cfg.post_norms_only:
        # Gemma-2 / OLMo-2: norm the attention OUTPUT before the residual
        attn_out = rms_norm(attn_out, lp["attn_out_norm"],
                            cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    x = res + attn_out

    res = x
    if not cfg.post_norms_only:
        x = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps,
                     cfg.rms_norm_add_one)
    if "moe" in lp:
        return res + _moe_mlp(cfg, lp["moe"], x)
    mp = lp["mlp"]
    inner = _activation(cfg)(proj(x, mp["gate"], "gate_proj")) * proj(
        x, mp["up"], "up_proj"
    )
    mlp_out = proj(inner, mp["down"], "down_proj")
    if cfg.sandwich_norms or cfg.post_norms_only:
        mlp_out = rms_norm(mlp_out, lp["ffw_out_norm"],
                           cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    return res + mlp_out


def _moe_mlp(cfg: ModelConfig, mp: dict, x: jax.Array) -> jax.Array:
    """Sparse-MoE MLP, HF Mixtral routing semantics: softmax over ALL expert
    logits, take top-k, renormalize the selected weights to sum to 1.

    Compute is the dense-expert formulation: every expert evaluates every
    token and the top-k mask zeroes the rest. That spends num_experts/top_k
    more FLOPs than a gather-based dispatch, but the shapes are static, every
    matmul is a large dense MXU op, and under expert parallelism GSPMD shards
    the E axis over the ep mesh axis — each device runs E/ep experts and the
    final combine psums over ep (+ tp on the inner axis). At serving batch
    sizes every expert is active anyway, so the "waste" is bounded and the
    alternative (capacity-factor dispatch à la GShard) drops tokens — wrong
    for inference. x: (B, T, h) → (B, T, h)."""
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    logits = (x @ mp["router"]).astype(jnp.float32)  # (B, T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    if cfg.norm_topk_prob:
        # Mixtral (always) / Qwen3-MoE norm_topk_prob=true: the selected
        # weights renormalize to sum to 1; otherwise they stay raw
        # softmax mass (HF Qwen3MoeSparseMoeBlock's "only diff")
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # (B, T, E) combine weights: topv scattered back onto the expert axis
    w = jnp.sum(
        jax.nn.one_hot(topi, e, dtype=jnp.float32) * topv[..., None], axis=-2
    )
    inner = _activation(cfg)(
        jnp.einsum("bth,ehi->btei", x, mp["gate"])
    ) * jnp.einsum("bth,ehi->btei", x, mp["up"])
    out = jnp.einsum("btei,eih->bteh", inner, mp["down"])
    return jnp.einsum("bteh,bte->bth", out, w.astype(x.dtype))


def _lora_layer_slice(lora: dict | None, i: int) -> dict | None:
    """Layer i's slice of the stacked adapter tree (scale is per-slot,
    layer-invariant)."""
    if lora is None:
        return None
    out: dict = {"scale": lora["scale"]}
    for name, mod in lora.items():
        if name != "scale":
            out[name] = {"A": mod["A"][:, i], "B": mod["B"][:, i]}
    return out


def _layer(
    cfg: ModelConfig,
    lp: dict,
    kv_layer: jax.Array,
    x: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    slot_mapping: jax.Array,
    mask: jax.Array | None,
    lora: dict | None = None,
    lora_idx: jax.Array | None = None,
    write_blocks: dict | None = None,  # blockwise-write inputs (see forward)
    pallas_prefill: dict | None = None,  # {"context_lens", "chunk_start",
    #   "interpret", "mesh"} — route attention through the paged
    #   flash-prefill kernel instead of the XLA gather (mask is None then)
) -> tuple[jax.Array, jax.Array]:
    b, t = x.shape[0], x.shape[1]
    hd, nkv = cfg.head_dim, cfg.num_kv_heads

    def attend(q, k, v):
        nonlocal kv_layer
        if write_blocks is not None:
            kv_layer = write_kv_pages_blockwise(
                kv_layer, k, v, write_blocks["ids"],
                write_blocks["start_off"], write_blocks["chunk_lens"],
            )
        else:
            kv_layer = write_kv_pages(
                kv_layer, k.reshape(b * t, nkv, hd),
                v.reshape(b * t, nkv, hd), slot_mapping,
            )
        if pallas_prefill is not None:
            mesh = pallas_prefill["mesh"]
            if mesh is not None and mesh.size > 1:
                return paged_prefill_attention_sharded(
                    mesh, q, kv_layer, block_tables,
                    pallas_prefill["context_lens"],
                    pallas_prefill["chunk_start"], scale=cfg.attn_scale,
                    interpret=pallas_prefill["interpret"],
                )
            return paged_prefill_attention(
                q, kv_layer, block_tables,
                pallas_prefill["context_lens"],
                pallas_prefill["chunk_start"], scale=cfg.attn_scale,
                interpret=pallas_prefill["interpret"],
            )
        return paged_attention_xla(
            q, kv_layer, block_tables, mask, scale=cfg.attn_scale,
            softcap=cfg.attn_logit_softcap,
        )

    x = _layer_body(cfg, lp, x, positions, attend, lora, lora_idx)
    return x, kv_layer


def forward(
    cfg: ModelConfig,
    params: dict,
    token_ids: jax.Array,  # (B, T) int32
    positions: jax.Array,  # (B, T) int32 logical positions
    kv_caches: jax.Array,  # (L, 2, num_blocks, block_size, kvH, D)
    block_tables: jax.Array,  # (B, max_blocks) int32
    slot_mapping: jax.Array,  # (B*T,) flat slots (padding -> block 0 slots)
    context_lens: jax.Array,  # (B,) tokens resident after this step
    lora: dict | None = None,  # stacked adapter tree (init_lora_params)
    lora_idx: jax.Array | None = None,  # (B,) adapter slot per row
    write_blocks: dict | None = None,  # {"ids": (B, NBW) written-span pool
    #   blocks, "start_off": (B,), "chunk_lens": (B,)} — when given, chunk
    #   K/V commits via the page-granular read-modify-write
    #   (ops/attention.py:write_kv_pages_blockwise) instead of the per-token
    #   row scatter; the serving prefill path passes this
    backend: str = "xla",  # "xla" | "pallas" | "pallas_interpret" — prefill
    #   attention path; pallas streams pool pages through the paged
    #   flash-prefill kernel and never builds the (B, T, S) mask
    mesh=None,  # required for the pallas backend on a >1-device mesh
) -> tuple[jax.Array, jax.Array]:
    """One model step over a token batch. Prefill is (B=1, T=chunk); decode is
    (B=batch, T=1). Returns (hidden (B,T,h), updated kv_caches)."""
    x = _embed(cfg, params, token_ids)
    if backend.startswith("pallas"):
        # the kernel masks from scalars alone — the scheduler feeds chunks
        # with contiguous positions (scheduler.py: range(start, start+len)),
        # so chunk_start is the first column. No (B, T, S) mask exists.
        if cfg.any_sliding:
            raise NotImplementedError(
                "pallas prefill does not support sliding-window models "
                "(the runner gates these to the XLA backend)"
            )
        mask = None
        mask_local = None
        pallas_prefill = {
            "context_lens": context_lens,
            "chunk_start": positions[:, 0],
            "interpret": backend == "pallas_interpret",
            "mesh": mesh,
        }
    else:
        # attention masks, built once per WINDOW KIND and reused by every
        # layer of that kind (full everywhere; plus the sliding variant
        # for Mistral-v0.1 / Gemma-2 class models)
        s_ctx = block_tables.shape[1] * kv_caches[0].shape[2]
        mask = causal_page_mask(positions, context_lens, s_ctx)
        mask_local = (
            causal_page_mask(positions, context_lens, s_ctx,
                             window=cfg.sliding_window)
            if cfg.any_sliding
            else None
        )
        pallas_prefill = None

    # unrolled layer loop (params stay stacked; each layer slices statically).
    # Unrolling instead of lax.scan lets each per-layer KV leaf alias its
    # donated input buffer — the scan alternatives all materialized a second
    # full pool (see init_kv_cache)
    new_kv: list[jax.Array] = []
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        x, layer_kv = _layer(
            cfg, lp, kv_caches[i], x, positions, block_tables, slot_mapping,
            mask_local if cfg.layer_sliding(i) else mask,
            _lora_layer_slice(lora, i), lora_idx, write_blocks,
            pallas_prefill,
        )
        new_kv.append(layer_kv)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    return x, tuple(new_kv)


def init_staged_kv(
    cfg: ModelConfig, window: int, batch: int, dtype: Any | None = None
) -> jax.Array:
    """Staging buffer for one fused decode window: (L, 2, W, B, kvH, D).
    Small (MBs), so carrying it through the window loop is cheap — unlike the
    pool itself (see paged_attention_with_staged)."""
    dt = jnp.dtype(dtype) if dtype is not None else _dtype(cfg)
    return jnp.zeros(
        (cfg.num_layers, 2, window, batch, cfg.num_kv_heads, cfg.head_dim), dt
    )


def decode_window_step(
    cfg: ModelConfig,
    params: dict,
    token_ids: jax.Array,  # (B,) this iteration's input token per row
    positions: jax.Array,  # (B,) this iteration's position per row
    kv_caches: tuple[jax.Array, ...],  # read-only pool
    block_tables: jax.Array,  # (B, max_blocks)
    staged: jax.Array,  # (L, 2, W, B, kvH, D) window staging buffer
    step_k: jax.Array,  # scalar int32: iteration index within the window
    hist_len: jax.Array,  # (B,): pool positions < hist_len are history
    backend: str = "xla",  # "xla" | "pallas" (TPU kernel) | "pallas_interpret"
    lora: dict | None = None,  # stacked adapter tree (init_lora_params)
    lora_idx: jax.Array | None = None,  # (B,) adapter slot per row
    hists: tuple | None = None,  # per-layer pre-gathered (hist_k, hist_v)
    mesh=None,  # required for the pallas backend on a >1-device mesh
) -> tuple[jax.Array, jax.Array]:
    """One decode iteration inside a fused window: reads the pool, writes this
    token's K/V into `staged` (not the pool — the pool stays loop-invariant so
    XLA doesn't ping-pong it through the loop carry; see
    ops/attention.py:paged_attention_with_staged). When the runner hoisted the
    loop-invariant history gather out of the window loop, `hists` carries the
    contiguous per-layer (hist_k, hist_v) and the pool is not touched here
    (ops/attention.py:attention_with_hist). Returns (hidden (B, h), staged')."""
    window = staged.shape[2]
    x = _embed(cfg, params, token_ids)[:, None]  # (B, 1, h)
    # staged slot w is attendable once written: w <= k
    staged_mask = jnp.arange(window, dtype=jnp.int32) <= step_k
    if backend == "xla":
        s_ctx = (
            hists[0][0].shape[1]
            if hists is not None
            else block_tables.shape[1] * kv_caches[0].shape[2]
        )
        arange = jnp.arange(s_ctx, dtype=jnp.int32)[None, :]
        hist_mask = arange < hist_len[:, None]
        hist_mask_local = None
        if cfg.any_sliding:
            # sliding layers: the query at `positions` sees only pool
            # history within the window. Staged slots stay globally
            # attendable — they are the most recent `decode_window`
            # positions, always inside any real sliding window (asserted
            # at engine init: sliding_window > decode_window)
            hist_mask_local = hist_mask & (
                arange > (positions - cfg.sliding_window)[:, None]
            )

    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])

        def attend(q, k, v, i=i):
            nonlocal staged
            staged = staged.at[i, 0, step_k].set(k[:, 0].astype(staged.dtype))
            staged = staged.at[i, 1, step_k].set(v[:, 0].astype(staged.dtype))
            if backend == "xla":
                h_mask = (
                    hist_mask_local
                    if cfg.layer_sliding(i)
                    else hist_mask
                )
                if hists is not None:
                    return attention_with_hist(
                        q, hists[i][0], hists[i][1], h_mask,
                        staged[i, 0], staged[i, 1], staged_mask,
                        scale=cfg.attn_scale,
                        softcap=cfg.attn_logit_softcap,
                    )
                return paged_attention_with_staged(
                    q, kv_caches[i], block_tables, h_mask,
                    staged[i, 0], staged[i, 1], staged_mask,
                    scale=cfg.attn_scale,
                    softcap=cfg.attn_logit_softcap,
                )
            if cfg.any_sliding or cfg.attn_logit_softcap:
                # self-enforcing invariant (the runner gates these models
                # to XLA): the decode kernel has no window masking or
                # softcap — silently wrong numerics otherwise
                raise NotImplementedError(
                    "pallas decode does not support sliding-window or "
                    "softcapped models"
                )
            if mesh is not None and mesh.size > 1:
                # pallas_call has no GSPMD partition rule — shard_map over
                # (dp, tp) places one kernel instance per device
                return paged_decode_attention_sharded(
                    mesh, q[:, 0], kv_caches[i], block_tables, hist_len,
                    staged[i, 0], staged[i, 1], step_k, scale=cfg.attn_scale,
                    interpret=backend == "pallas_interpret",
                )[:, None]
            return paged_decode_attention(
                q[:, 0], kv_caches[i], block_tables, hist_len,
                staged[i, 0], staged[i, 1], step_k, scale=cfg.attn_scale,
                interpret=backend == "pallas_interpret",
            )[:, None]

        x = _layer_body(
            cfg, lp, x, positions[:, None], attend,
            _lora_layer_slice(lora, i), lora_idx,
        )
    x = rms_norm(x[:, 0], params["final_norm"], cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    return x, staged


def commit_staged_kv(
    kv_caches: tuple[jax.Array, ...],
    staged: jax.Array,  # (L, 2, W, B, kvH, D)
    slot_mapping: jax.Array,  # (B*W,) flat pool slots, row-major (b, w)
) -> tuple[jax.Array, ...]:
    """Scatter a whole window's staged K/V into the (donated) pool, one
    scatter per layer — the only pool write of the fused decode window."""
    L, _, w, b, kvh, d = staged.shape
    new_kv: list[jax.Array] = []
    for i in range(L):
        k_rows = jnp.moveaxis(staged[i, 0], 0, 1).reshape(b * w, kvh, d)
        v_rows = jnp.moveaxis(staged[i, 1], 0, 1).reshape(b * w, kvh, d)
        new_kv.append(write_kv_pages(kv_caches[i], k_rows, v_rows, slot_mapping))
    return tuple(new_kv)


def embed_encode(
    cfg: ModelConfig,
    params: dict,
    token_ids: jax.Array,  # (B, T) int32 (rows padded with 0s)
    lengths: jax.Array,  # (B,) true lengths
) -> jax.Array:
    """Plain causal self-attention encode (no paged KV): final-layer hidden
    state at each row's LAST real token, L2-normalized — the /v1/embeddings
    path (vLLM serves decoder embeddings the same way: last-token pooling).
    Returns (B, h) float32."""
    b, t = token_ids.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    x = _embed(cfg, params, token_ids)
    mask = causal_page_mask(positions, lengths, t)  # (B, T, T)
    mask_local = (
        causal_page_mask(positions, lengths, t, window=cfg.sliding_window)
        if cfg.any_sliding
        else None
    )

    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        m = mask_local if cfg.layer_sliding(i) else mask

        def attend(q, k, v, m=m):
            return masked_attention(
                q, k, v, m, scale=cfg.attn_scale,
                softcap=cfg.attn_logit_softcap,
            )

        x = _layer_body(cfg, lp, x, positions, attend)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0].astype(jnp.float32)  # (B, h)
    return last / jnp.maximum(
        jnp.linalg.norm(last, axis=-1, keepdims=True), 1e-9
    )


def forward_sp_prefill(
    cfg: ModelConfig,
    params: dict,
    token_ids: jax.Array,  # (B, T) chunk tokens, T sharded over sp
    positions: jax.Array,  # (B, T) GLOBAL positions of the chunk
    kv_caches: tuple[jax.Array, ...],
    block_tables: jax.Array,  # (B, max_blocks)
    slot_mapping: jax.Array,  # (B*T,) flat pool slots for the chunk
    chunk_lens: jax.Array,  # (B,) real tokens in this chunk per row
    hist_lens: jax.Array,  # (B,) already-resident context before the chunk
    mesh,  # the engine mesh (must carry an sp axis > 1 to be useful)
    lora: dict | None = None,
    lora_idx: jax.Array | None = None,
) -> tuple[jax.Array, tuple[jax.Array, ...]]:
    """The engine's CHUNKED-PREFILL step with the chunk's sequence axis
    sharded over the sp mesh axis: attention is ring attention
    (parallel/ring_attention.py) seeded with the pooled history block, so it
    supports exactly the same chunk-by-chunk contract as `forward` while no
    device ever holds a (T, T) score matrix or the whole chunk's activations.
    Projections / norms / MLP are token-parallel and shard over sp for free
    under GSPMD. Chunk K/V are written to the pool AFTER attention (the ring
    provides the chunk's own causality; the pool provides history).

    Returns (hidden (B, T, h) sp-sharded, updated kv_caches)."""
    from ..parallel.ring_attention import ring_attention

    b, t = token_ids.shape
    kv_valid = (
        jnp.arange(t, dtype=jnp.int32)[None, :] < chunk_lens[:, None]
    )  # (B, T) real chunk tokens
    x = _embed(cfg, params, token_ids)
    nkv, hd = cfg.num_kv_heads, cfg.head_dim

    new_kv: list[jax.Array] = []
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])

        def attend(q, k, v, i=i):
            hist_k, hist_v = gather_pages(kv_caches[i], block_tables)
            # quantized pools convert to the compute dtype as they stream in
            hist_k = hist_k.astype(q.dtype)
            hist_v = hist_v.astype(q.dtype)
            out = ring_attention(
                mesh, q, k, v, positions, kv_valid, scale=cfg.attn_scale,
                hist_k=hist_k, hist_v=hist_v, hist_len=hist_lens,
            )
            new_kv.append(
                write_kv_pages(
                    kv_caches[i],
                    k.reshape(b * t, nkv, hd),
                    v.reshape(b * t, nkv, hd),
                    slot_mapping,
                )
            )
            return out

        x = _layer_body(
            cfg, lp, x, positions, attend, _lora_layer_slice(lora, i), lora_idx
        )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    return x, tuple(new_kv)


def forward_context_parallel(
    cfg: ModelConfig,
    params: dict,
    token_ids: jax.Array,  # (B, T) int32, T sharded over the sp mesh axis
    lengths: jax.Array,  # (B,) true lengths (padding rows masked out)
    mesh,  # jax.sharding.Mesh with an "sp" axis
) -> tuple[jax.Array, jax.Array]:
    """Long-context prefill with the SEQUENCE axis sharded over the mesh's sp
    axis: every layer's attention runs as ring attention
    (parallel/ring_attention.py — flash accumulation + ppermute K/V rotation),
    so no device ever materializes the full (T, T) score matrix or the full
    sequence's K/V. Projections/norms/MLP are token-parallel and shard over
    sp for free under GSPMD.

    Returns (hidden (B, T, h) sp-sharded, per-layer stacked KV
    (L, 2, B, T, kvH, D) for the caller to commit into the paged pool).
    The reference inherits this capability from its engines' context-parallel
    attention; this is the TPU-native construction (SURVEY §2.4).
    """
    from ..parallel.ring_attention import ring_attention

    b, t = token_ids.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    kv_valid = positions < lengths[:, None]
    x = _embed(cfg, params, token_ids)

    kv_out: list[jax.Array] = []
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])

        def attend(q, k, v):
            kv_out.append(jnp.stack([k, v]).astype(_dtype(cfg)))
            return ring_attention(
                mesh, q, k, v, positions, kv_valid, scale=cfg.head_dim**-0.5
            )

        x = _layer_body(cfg, lp, x, positions, attend)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, cfg.rms_norm_add_one,
                 scale_f32=cfg.norm_scale_f32)
    return x, jnp.stack(kv_out)


def compute_logits(cfg: ModelConfig, params: dict, hidden: jax.Array) -> jax.Array:
    """hidden: (N, h) -> logits (N, vocab) in float32 (Gemma-2 applies a
    final tanh softcap)."""
    if cfg.tie_word_embeddings:
        logits = (hidden @ params["embed"].T).astype(jnp.float32)
    else:
        logits = _mm(hidden, params["lm_head"]).astype(jnp.float32)
    from ..ops.attention import _softcap

    return _softcap(logits, cfg.final_logit_softcap)
