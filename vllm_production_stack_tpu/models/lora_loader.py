"""PEFT-format LoRA adapter loading.

The reference's LoRA controller downloads adapters (HF/S3/local) to a shared
PVC and hot-loads them into engines via /v1/load_lora_adapter
(loraadapter_controller.go:334-391, 582-611). This module parses the on-disk
artifact it ships: a PEFT adapter dir with `adapter_config.json` (r,
lora_alpha, target_modules) and `adapter_model.safetensors` with keys like

    base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight  (r, in)
    base_model.model.model.layers.{i}.self_attn.q_proj.lora_B.weight  (out, r)

mapped into the engine's stacked slot buffers (models/llama.py
init_lora_params): A → (L, in, max_rank), B → (L, max_rank, out), transposed
to (in, out) orientation and zero-padded from the adapter's rank r to
max_lora_rank so every slot shares one shape (no recompile on load).
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..engine.config import LoRAConfig, ModelConfig
from .llama import lora_module_dims

_MODULE_PARENTS = {
    "q_proj": "self_attn",
    "k_proj": "self_attn",
    "v_proj": "self_attn",
    "o_proj": "self_attn",
    "gate_proj": "mlp",
    "up_proj": "mlp",
    "down_proj": "mlp",
}


class LoRAAdapter:
    """Parsed adapter: per-module stacked (L, in, max_rank)/(L, max_rank, out)
    numpy arrays + the PEFT scaling alpha/r."""

    def __init__(self, modules: dict[str, dict[str, np.ndarray]], scale: float,
                 rank: int):
        self.modules = modules
        self.scale = scale
        self.rank = rank


def load_lora_adapter(
    path: str, model_cfg: ModelConfig, lora_cfg: LoRAConfig
) -> LoRAAdapter:
    from safetensors import safe_open

    with open(os.path.join(path, "adapter_config.json")) as f:
        acfg = json.load(f)
    rank = int(acfg["r"])
    alpha = float(acfg.get("lora_alpha", rank))
    targets = acfg.get("target_modules") or []
    if rank > lora_cfg.max_lora_rank:
        raise ValueError(
            f"adapter rank {rank} exceeds max_lora_rank="
            f"{lora_cfg.max_lora_rank}; raise it in LoRAConfig"
        )
    unsupported = [t for t in targets if t not in _MODULE_PARENTS]
    if unsupported:
        raise ValueError(f"unsupported LoRA target modules {unsupported}")
    untargetable = [t for t in targets if t not in lora_cfg.target_modules]
    if untargetable:
        raise ValueError(
            f"adapter targets {untargetable} but the engine only reserves "
            f"buffers for {lora_cfg.target_modules}"
        )

    sft = os.path.join(path, "adapter_model.safetensors")
    dims = lora_module_dims(model_cfg)
    dt = np.dtype("float32") if model_cfg.dtype == "float32" else None
    with safe_open(sft, framework="np") as f:
        keys = set(f.keys())

        def get(name: str) -> np.ndarray:
            # PEFT key prefixes vary slightly across versions
            for prefix in (
                "base_model.model.model.layers.",
                "base_model.model.layers.",
            ):
                k = prefix + name
                if k in keys:
                    return f.get_tensor(k)
            raise KeyError(f"missing LoRA tensor ...{name}")

        modules: dict[str, dict[str, np.ndarray]] = {}
        L, r_max = model_cfg.num_layers, lora_cfg.max_lora_rank
        for mod in targets:
            din, dout = dims[mod]
            a = np.zeros((L, din, r_max), np.float32)
            b = np.zeros((L, r_max, dout), np.float32)
            parent = _MODULE_PARENTS[mod]
            for i in range(L):
                # PEFT lora_A (r, in) -> ours (in, r); lora_B (out, r) -> (r, out)
                a[i, :, :rank] = get(f"{i}.{parent}.{mod}.lora_A.weight").T
                b[i, :rank, :] = get(f"{i}.{parent}.{mod}.lora_B.weight").T
            modules[mod] = {"A": a, "B": b}
    if dt is not None:
        modules = {
            m: {k: v.astype(dt) for k, v in mm.items()}
            for m, mm in modules.items()
        }
    return LoRAAdapter(modules, scale=alpha / rank, rank=rank)
