"""Model name/path resolution to ModelConfig.

Named presets cover the baseline configs (BASELINE.md: opt-125m-class tiny
models for CI, Llama-3-8B for the headline benchmark, Llama-3-70B for
pipeline parallel); a local directory with an HF config.json is parsed
directly (zero-egress environments can't download)."""

from __future__ import annotations

import json
import os

from ..engine.config import ModelConfig

# Architecture hyperparameters follow the public model cards.
PRESETS: dict[str, dict] = {
    "tiny-llama": dict(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, max_model_len=256,
        dtype="float32",
    ),
    # CI-class small model (stands in for facebook/opt-125m in the reference's
    # minikube tests, tests/e2e/run-k8s-routing-test.sh)
    "debug-125m": dict(
        vocab_size=32000, hidden_size=768, intermediate_size=2048,
        num_layers=12, num_heads=12, num_kv_heads=12, head_dim=64,
        max_model_len=2048, rope_theta=10000.0,
    ),
    "llama-1b": dict(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        max_model_len=8192, rope_theta=500000.0,
    ),
    # Llama-3.2-3B shape: the biggest bf16 preset that fits ONE v5e chip
    # (≈6.0 GiB weights) with KV headroom — the north-star bench model
    "llama-3b": dict(
        vocab_size=128256, hidden_size=3072, intermediate_size=8192,
        num_layers=28, num_heads=24, num_kv_heads=8, head_dim=128,
        max_model_len=8192, rope_theta=500000.0, tie_word_embeddings=True,
    ),
    "llama-3-8b": dict(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        max_model_len=8192, rope_theta=500000.0,
    ),
    "llama-3-70b": dict(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_layers=80, num_heads=64, num_kv_heads=8, head_dim=128,
        max_model_len=8192, rope_theta=500000.0,
    ),
    "qwen2-7b": dict(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
        max_model_len=8192, rope_theta=1000000.0, attention_bias=True,
        architecture="qwen2",
    ),
    "qwen3-8b": dict(
        vocab_size=151936, hidden_size=4096, intermediate_size=12288,
        num_layers=36, num_heads=32, num_kv_heads=8, head_dim=128,
        max_model_len=8192, rope_theta=1000000.0, architecture="qwen3",
        qk_norm=True,
    ),
    "tiny-gemma": dict(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=24, max_model_len=256,
        dtype="float32", architecture="gemma", hidden_act="gelu_tanh",
        rms_norm_add_one=True, scale_embeddings=True,
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    ),
    "gemma-7b": dict(
        vocab_size=256000, hidden_size=3072, intermediate_size=24576,
        num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
        max_model_len=8192, rope_theta=10000.0, architecture="gemma",
        hidden_act="gelu_tanh", rms_norm_add_one=True, scale_embeddings=True,
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    ),
    "gemma2-9b": dict(
        vocab_size=256000, hidden_size=3584, intermediate_size=14336,
        num_layers=42, num_heads=16, num_kv_heads=8, head_dim=256,
        max_model_len=8192, rope_theta=10000.0, architecture="gemma2",
        hidden_act="gelu_tanh", rms_norm_add_one=True, scale_embeddings=True,
        tie_word_embeddings=True, rms_norm_eps=1e-6, sandwich_norms=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=224,  # hidden/heads = 3584/16 (NOT head_dim)
        sliding_window=4096,
        sliding_window_pattern=2,
    ),
    "mixtral-8x7b": dict(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        max_model_len=8192, rope_theta=1000000.0, architecture="mixtral",
        num_experts=8, num_experts_per_tok=2,
    ),
    "tiny-mixtral": dict(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, max_model_len=256,
        dtype="float32", architecture="mixtral", num_experts=4,
        num_experts_per_tok=2,
    ),
}

_ARCH_MAP = {
    "LlamaForCausalLM": "llama",
    "MistralForCausalLM": "llama",
    "Qwen2ForCausalLM": "qwen2",
    "Qwen3ForCausalLM": "qwen3",
    "Phi3ForCausalLM": "phi3",
    "Qwen3MoeForCausalLM": "qwen3moe",
    "Olmo2ForCausalLM": "olmo2",
    "MixtralForCausalLM": "mixtral",
    "GemmaForCausalLM": "gemma",
    "Gemma2ForCausalLM": "gemma2",
}


def resolve_model_config(
    model: str,
    max_model_len: int | None = None,
    dtype: str | None = None,
    quantization: str | None = None,
) -> ModelConfig:
    """model: a preset name, or a local HF checkpoint dir (config.json)."""
    if model in PRESETS:
        kw = dict(PRESETS[model])
        kw["model"] = model
    elif os.path.isdir(model) and os.path.exists(os.path.join(model, "config.json")):
        kw = _from_hf_config(model)
    else:
        raise ValueError(
            f"unknown model '{model}': not a preset "
            f"({', '.join(PRESETS)}) and not a local checkpoint dir"
        )
    if max_model_len is not None:
        kw["max_model_len"] = max_model_len
    if dtype is not None:
        kw["dtype"] = dtype
    if quantization is not None:
        kw["quantization"] = quantization
    kw.setdefault("dtype", "bfloat16")
    return ModelConfig(**kw)


def _from_hf_config(path: str) -> dict:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    archs = hf.get("architectures", [])
    arch = next((_ARCH_MAP[a] for a in archs if a in _ARCH_MAP), None)
    if arch is None:
        raise ValueError(f"unsupported architecture(s) {archs} in {path}")
    heads = hf["num_attention_heads"]
    moe = (
        dict(
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        )
        if arch == "mixtral"
        else {}
    )
    if arch == "qwen3moe":
        if hf.get("mlp_only_layers") or (hf.get("decoder_sparse_step", 1)
                                         != 1):
            raise ValueError(
                "qwen3moe with dense layers interleaved is not "
                f"implemented ({path})"
            )
        moe = dict(
            # HF use_diff serialization omits class-default fields —
            # fall back to Qwen3MoeConfig's defaults (which are the
            # published Qwen3-30B-A3B values)
            num_experts=hf.get("num_experts", 128),
            num_experts_per_tok=hf.get("num_experts_per_tok", 8),
            norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
            # all layers are MoE: the expert inner width IS the
            # intermediate size our expert tree uses
            intermediate_size=hf.get("moe_intermediate_size", 768),
        )
    gemma = (
        dict(
            hidden_act="gelu_tanh", rms_norm_add_one=True,
            scale_embeddings=True,
        )
        if arch == "gemma"
        else {}
    )
    if arch == "gemma2":
        gemma = dict(
            hidden_act="gelu_tanh", rms_norm_add_one=True,
            scale_embeddings=True, sandwich_norms=True,
            attn_logit_softcap=float(hf.get("attn_logit_softcapping") or 0),
            final_logit_softcap=float(
                hf.get("final_logit_softcapping") or 0
            ),
            query_pre_attn_scalar=int(hf.get("query_pre_attn_scalar") or 0),
            sliding_window=int(hf.get("sliding_window") or 0),
            sliding_window_pattern=2,  # HF layer_types: even layers slide
        )
    # per-architecture norm/attention convention flags
    arch_flags = (
        dict(qk_norm=True) if arch in ("qwen3", "qwen3moe") else {}
    )
    if arch == "olmo2":
        arch_flags = dict(qk_norm_flat=True, post_norms_only=True,
                          norm_scale_f32=True)
    # sliding-window attention: Mistral-7B-v0.1 sets sliding_window=4096
    # on every layer (v0.2+ configs carry null). Silently serving full
    # attention would give wrong numerics past the window.
    sw = {}
    if (
        ("MistralForCausalLM" in archs or arch == "phi3")
        and hf.get("sliding_window")
    ):
        sw = dict(sliding_window=int(hf["sliding_window"]),
                  sliding_window_pattern=1)
    # RoPE scaling (Llama-3.1-class checkpoints — the reference's headline
    # model ships rope_scaling rope_type=llama3): silently ignoring it
    # would serve subtly wrong long-range positions, so unknown types are
    # a hard error, not a warning
    # partial rotary (Phi-4-mini class): unimplemented — refusing beats
    # silently serving full-rotary numerics that diverge from HF
    prf = hf.get("partial_rotary_factor")
    if prf is not None and float(prf) != 1.0:
        raise ValueError(
            f"unsupported partial_rotary_factor {prf} in {path} "
            "(only full rotary is implemented)"
        )
    rs = hf.get("rope_scaling") or {}
    rs_type = rs.get("rope_type") or rs.get("type")
    if rs_type in (None, "default"):
        scaling = {}
    elif rs_type in ("llama3", "linear"):
        scaling = dict(
            rope_scaling_type=rs_type,
            rope_scaling_factor=float(rs.get("factor", 1.0)),
            rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
            rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
            rope_original_max_position=int(
                rs.get("original_max_position_embeddings", 8192)
            ),
        )
    else:
        raise ValueError(
            f"unsupported rope_scaling type {rs_type!r} in {path} "
            "(supported: llama3, linear)"
        )
    # dict() right-most wins: the explicit intermediate_size below would
    # clobber a MoE-specific expert width, so hoist it first
    inter = moe.pop("intermediate_size", None)
    if inter is None:  # lazy: qwen3moe configs may omit the dense field
        inter = hf["intermediate_size"]
    return dict(
        **moe,
        **gemma,
        **arch_flags,
        **sw,
        **scaling,
        model=path,
        architecture=arch,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=inter,
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        # some configs carry an explicit null head_dim — fall through to the
        # conventional hidden/heads in that case too
        head_dim=hf.get("head_dim") or hf["hidden_size"] // heads,
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_model_len=hf.get("max_position_embeddings", 4096),
        # Gemma ties by default and HF omits class-default fields from
        # config.json, so the fallback is architecture-dependent
        tie_word_embeddings=hf.get(
            "tie_word_embeddings", arch in ("gemma", "gemma2")
        ),
        attention_bias=hf.get("attention_bias", arch == "qwen2"),
        checkpoint=path,
        tokenizer=path,
    )
