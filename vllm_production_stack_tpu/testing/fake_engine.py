"""Fake TPU engine: an OpenAI+metrics server emitting tokens at a fixed rate.

The perf rig the router is tested against without hardware — the reference's
fake-openai-server (src/tests/perftest/fake-openai-server.py) plays this role
for its CI (router-e2e-test.yml:51-87). Speaks exactly the surface the router
consumes: /v1/models, /v1/chat/completions, /v1/completions (stream and not),
/metrics with the `tpu:*` contract, /health, /sleep /wake_up /is_sleeping.

Run: python -m vllm_production_stack_tpu.testing.fake_engine --port 9001 \
        --model fake-llama --tokens-per-sec 500
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid

from aiohttp import web

from .. import metrics_contract as mc
from ..fleet import ConvergenceMeter, SessionStickinessAudit


class FakeEngine:
    # prompt-prefix key length for the warm-prefix model: long enough to
    # distinguish workloads sharing a short greeting, short enough that a
    # shared system prompt + per-user tail maps to ONE key
    WARM_KEY_CHARS = 128

    def __init__(
        self,
        model: str = "fake-model",
        tokens_per_sec: float = 500.0,
        default_tokens: int = 64,
        model_label: str = "",
        self_url: str = "",
        log_requests: bool = True,
        seats: int = 0,
        prefill_tps: float = 0.0,
        peer_pull_tps: float = 0.0,
        kv_bytes_per_token: float = 0.0,
        role: str = "",
        kv_controller_url: str = "",
    ):
        self.model = model
        self.tokens_per_sec = tokens_per_sec
        self.default_tokens = default_tokens
        self.model_label = model_label
        self.running = 0
        self.total_requests = 0
        self.prompt_tokens_total = 0
        self.generation_tokens_total = 0
        self.sleeping = False
        # -- peer-tier bench model (docs/35-peer-kv-reuse.md) --------------
        # seats > 0 bounds concurrent decodes (requests queue FIFO for a
        # seat — the queue wait a hot owner accumulates); prefill_tps > 0
        # charges len(prompt)/4/prefill_tps of prefill delay for COLD
        # prompts (a warm prefix is free); peer_pull_tps > 0 makes an
        # x-kv-owner-hint request pay the (much cheaper) pull delay once,
        # after which the prefix is warm locally.
        self.seats = seats
        self._seat_sem = asyncio.Semaphore(seats) if seats > 0 else None
        self.prefill_tps = prefill_tps
        self.peer_pull_tps = peer_pull_tps
        # exported so the router's priced scoring can price migrations
        # against this engine exactly as it would a real one
        self.kv_bytes_per_token = kv_bytes_per_token
        self.warm_prefixes: set[str] = set()
        self.peer_pulls = 0
        self.cold_prefills = 0
        # -- pool-rebalancing surface (docs/40-pool-rebalancing.md) --------
        # the role splits the load model: a "prefill" engine never takes a
        # decode seat (its capacity is prefill_tps), a "decode"/roleless
        # engine queues for seats. POST /role flips it live and
        # re-registers with the KV controller, exactly the flow the
        # rebalancer drives against real engines.
        self.role = role
        self.self_url = self_url
        self.kv_controller_url = kv_controller_url.rstrip("/")
        self.draining = False
        self.seats_busy = 0
        self.role_flips = 0
        # seat queue wait, rendered as the contract histogram the router
        # scraper computes its per-scrape p95 delta from (render-only:
        # nothing drains into a prometheus registry here)
        self.queue_wait = ConvergenceMeter(buffer_pending=False)
        # the REAL engine-side stickiness audit (fleet.py) over the
        # router's sticky stamps, so multi-replica benches measure
        # violations through the same detector production uses; self_url
        # arms non_owner_delivery (the fleet_scale bench passes it)
        self.stickiness = SessionStickinessAudit(self_url=self_url or None)
        # off for open-loop load benches: an unbounded per-request log
        # would grow by the full request volume
        self.log_requests = log_requests
        self.seen_request_log: list[dict] = []  # tests inspect who got what

    # -- handlers ----------------------------------------------------------

    async def h_models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": self.model,
                        "object": "model",
                        "created": 0,
                        "owned_by": "fake-tpu",
                    }
                ],
            }
        )

    async def h_completion(self, request: web.Request) -> web.StreamResponse:
        body = await request.json()
        if self.sleeping:
            return web.json_response(
                {"error": {"message": "engine is asleep"}}, status=503
            )
        if self.draining:
            # the real engine's drain barrier answers 503 with this header
            # so the router retries elsewhere instead of counting an error
            return web.json_response(
                {"error": {"message": "engine is draining"}},
                status=503,
                headers={"X-Engine-Draining": "1"},
            )
        self.total_requests += 1
        self.stickiness.observe_headers(request.headers)
        if self.log_requests:
            self.seen_request_log.append(
                {"path": request.path, "body": body, "t": time.time(),
                 # lowercased so tests can assert on router-stamped tenant
                 # headers without caring about wire casing
                 "headers": {k.lower(): v for k, v in request.headers.items()}}
            )
        is_chat = request.path.endswith("chat/completions")
        n = int(body.get("max_tokens") or self.default_tokens)
        prompt = body.get("prompt") or json.dumps(body.get("messages", []))
        n_prompt = max(1, len(str(prompt)) // 4)
        self.prompt_tokens_total += n_prompt
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        # testing-only knob: a body-level token rate overrides the server
        # default, so one fake fleet can serve a fast non-stream throughput
        # phase and a slow long-hold stream phase in the same bench run
        gap = 1.0 / float(body.get("tokens_per_sec") or self.tokens_per_sec)

        self.running += 1
        try:
            # seat gate FIRST: queue wait at a saturated engine delays the
            # first byte exactly like a real scheduler's waiting queue
            # (self.running already counts this request, so the router's
            # scraped load sees the backlog). A "prefill"-role engine is
            # NOT seat-gated — its capacity is prefill_tps, which is what
            # makes a role flip actually move decode capacity.
            gated = self._seat_sem is not None and self.role != "prefill"
            t0 = time.monotonic()
            if gated:
                await self._seat_sem.acquire()
            # every admitted request observes its seat wait (0 when
            # un-gated) so the rendered queue-wait histogram carries the
            # same signal the real scheduler's does
            self.queue_wait.observe(time.monotonic() - t0)
            if gated:
                self.seats_busy += 1
            try:
                await self._prefill_delay(str(prompt), n_prompt, request)
                return await self._emit(
                    request, body, rid, created, is_chat, n, n_prompt, gap
                )
            finally:
                if gated:
                    self.seats_busy -= 1
                    self._seat_sem.release()
        finally:
            self.running -= 1

    async def _prefill_delay(self, prompt: str, n_prompt: int,
                             request: web.Request) -> None:
        """Warm/cold/peer-pull prefill model (no-op unless prefill_tps is
        configured): a warm prefix is free; an owner-hinted request pays
        matched/peer_pull_tps then warms the prefix; a cold prompt pays
        the full n_prompt/prefill_tps."""
        if self.prefill_tps <= 0:
            return
        key = prompt[: self.WARM_KEY_CHARS]
        if key in self.warm_prefixes:
            return
        hint = request.headers.get("x-kv-owner-hint")
        if hint and self.peer_pull_tps > 0:
            self.peer_pulls += 1
            await asyncio.sleep(n_prompt / self.peer_pull_tps)
        else:
            self.cold_prefills += 1
            await asyncio.sleep(n_prompt / self.prefill_tps)
        self.warm_prefixes.add(key)

    @staticmethod
    def _structured_text(body: dict) -> str | None:
        """Schema-valid body for a structured-output request
        (docs/41-structured-output.md), or None for free-form requests.
        Uses the real jax-free surface helpers so the fake honors exactly
        the requests a real engine would constrain — router e2e tests can
        assert the body parses under the declared schema."""
        rf = body.get("response_format")
        gj = body.get("guided_json")
        if rf is None and gj is None:
            return None
        from ..engine.grammar import (
            GrammarCompileError,
            extract_spec,
            schema_instance,
        )

        try:
            spec = extract_spec(rf, gj)
        except GrammarCompileError:
            return None
        if spec is None:
            return None
        if spec.get("kind") == "json_schema":
            return json.dumps(
                schema_instance(spec["schema"]), separators=(",", ":")
            )
        return "{}"

    async def _emit(self, request, body, rid, created, is_chat, n,
                    n_prompt, gap) -> web.StreamResponse:
        structured = self._structured_text(body)
        if structured is not None:
            # the constrained body replaces the tokN filler; the emission
            # pacing (gap per chunk) stays, so latency-model benches are
            # undisturbed by WHAT is emitted
            pieces = [structured[i:i + 8] for i in range(0, len(structured), 8)]
            n = len(pieces)
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for i in range(n):
                await asyncio.sleep(gap)
                piece = pieces[i] if structured is not None else f"tok{i} "
                delta = (
                    {"delta": {"content": piece}}
                    if is_chat
                    else {"text": piece}
                )
                chunk = {
                    "id": rid,
                    "object": (
                        "chat.completion.chunk" if is_chat else "text_completion"
                    ),
                    "created": created,
                    "model": body.get("model", self.model),
                    "choices": [{"index": 0, **delta, "finish_reason": None}],
                }
                await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
            opts = body.get("stream_options") or {}
            if opts.get("include_usage"):
                usage_chunk = {
                    "id": rid,
                    "object": (
                        "chat.completion.chunk" if is_chat
                        else "text_completion"
                    ),
                    "created": created,
                    "model": body.get("model", self.model),
                    "choices": [],
                    "usage": {
                        "prompt_tokens": n_prompt,
                        "completion_tokens": n,
                        "total_tokens": n_prompt + n,
                    },
                }
                await resp.write(
                    f"data: {json.dumps(usage_chunk)}\n\n".encode()
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            self.generation_tokens_total += n
            return resp
        await asyncio.sleep(gap * n)
        self.generation_tokens_total += n
        if structured is not None:
            text, finish = structured, "stop"
        else:
            text, finish = " ".join(f"tok{i}" for i in range(n)), "length"
        choice = (
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }
            if is_chat
            else {"index": 0, "text": text, "finish_reason": finish}
        )
        return web.json_response(
            {
                "id": rid,
                "object": "chat.completion" if is_chat else "text_completion",
                "created": created,
                "model": body.get("model", self.model),
                "choices": [choice],
                "usage": {
                    "prompt_tokens": n_prompt,
                    "completion_tokens": n,
                    "total_tokens": n_prompt + n,
                },
            }
        )

    async def h_transcription(self, request: web.Request) -> web.Response:
        """Echo the multipart upload back: proves the router relayed the file
        bytes and form fields intact (shape of a Whisper-class response)."""
        self.total_requests += 1
        form = await request.post()
        if "file" not in form or "model" not in form:
            return web.json_response(
                {"error": {"message": "missing form field"}}, status=400
            )
        f = form["file"]
        payload = f.file.read()
        fields = {
            k: v for k, v in form.items() if not isinstance(v, web.FileField)
        }
        self.seen_request_log.append(
            {"path": "/v1/audio/transcriptions", "fields": fields,
             "filename": f.filename, "bytes": len(payload)}
        )
        return web.json_response(
            {
                "text": f"transcribed {len(payload)} bytes of {f.filename}",
                "model": fields.get("model"),
                "fields": fields,
            }
        )

    async def h_embeddings(self, request: web.Request) -> web.Response:
        """Deterministic per-input embedding (the real engine's
        /v1/embeddings shape) — identical inputs get identical vectors, so
        the router's engine-backed semantic cache is testable."""
        import hashlib

        import numpy as np

        body = await request.json()
        raw = body.get("input", "")
        inputs = raw if isinstance(raw, list) else [raw]
        data = []
        for i, text in enumerate(inputs):
            seed = int.from_bytes(
                hashlib.sha256(str(text).encode()).digest()[:4], "little"
            )
            v = np.random.RandomState(seed).randn(64).astype(np.float32)
            v /= np.linalg.norm(v)
            data.append({"object": "embedding", "index": i,
                         "embedding": [float(x) for x in v]})
        return web.json_response({
            "object": "list", "model": body.get("model", self.model),
            "data": data,
            "usage": {"prompt_tokens": 1, "total_tokens": 1},
        })

    async def h_metrics(self, request: web.Request) -> web.Response:
        label = f'{{model_name="{self.model}"}}'
        lines = [
            f"# TYPE {mc.NUM_REQUESTS_RUNNING.replace(':', '_')} gauge",
            f"{mc.NUM_REQUESTS_RUNNING}{label} {self.running}",
            f"{mc.NUM_REQUESTS_WAITING}{label} 0",
            f"{mc.HBM_KV_USAGE_PERC}{label} {min(1.0, self.running * 0.1):.3f}",
            f"{mc.PREFIX_CACHE_HIT_RATE}{label} 0.5",
            f"{mc.PREFIX_CACHE_HITS}{label} {self.total_requests * 2}",
            f"{mc.PREFIX_CACHE_QUERIES}{label} {self.total_requests * 4}",
            f"{mc.PROMPT_TOKENS}{label} {self.prompt_tokens_total}",
            f"{mc.GENERATION_TOKENS}{label} {self.generation_tokens_total}",
        ]
        if self.kv_bytes_per_token > 0:
            # peer-tier pricing inputs (docs/35-peer-kv-reuse.md), shaped
            # exactly like the real exporter so the router's priced
            # route-vs-migrate scoring reads this fake the same way:
            # bytes/token plus a "measured" peer-in bandwidth derived
            # from the configured pull rate
            lines.append(
                f"{mc.KV_BYTES_PER_TOKEN}{label} "
                f"{self.kv_bytes_per_token}"
            )
            if self.peer_pull_tps > 0:
                bw = self.peer_pull_tps * self.kv_bytes_per_token
                lines.append(
                    f'{mc.KV_TIER_BANDWIDTH}{{model_name="{self.model}",'
                    f'tier="peer",direction="in"}} {bw}'
                )
        # pool-rebalancing signal surface (docs/40-pool-rebalancing.md):
        # one-hot role, decode-seat occupancy, and the cumulative
        # queue-wait histogram — the three series the router scraper
        # derives role / seat_occupancy / per-scrape p95 from
        for value in mc.POOL_ROLE_VALUES:
            lines.append(
                f'{mc.POOL_ROLE}{{model_name="{self.model}",'
                f'role="{value}"}} {1 if value == self.role else 0}'
            )
        if self.seats > 0:
            occ = (
                self.seats_busy / self.seats if self.role != "prefill"
                else 0.0
            )
            lines.append(
                f"{mc.ENGINE_DECODE_SEAT_OCCUPANCY}{label} {occ:.3f}"
            )
        lines.extend(self.queue_wait.render(mc.REQUEST_QUEUE_WAIT))
        # stickiness-audit contract series (closed reason set), so the
        # multi-replica benches read violations the same way a scraper
        # would off a real engine
        base = mc.SESSION_STICKINESS_VIOLATIONS
        lines.append(f"# TYPE {base} counter")
        for reason, n in sorted(self.stickiness.counts().items()):
            lines.append(f'{base}{{reason="{reason}"}} {n}')
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def h_debug_stickiness(self, request: web.Request) -> web.Response:
        return web.json_response(self.stickiness.snapshot())

    async def h_health(self, request: web.Request) -> web.Response:
        # role + draining ride along so the rebalancer's rejoin gate can
        # confirm the engine serves under the role it was flipped to
        return web.json_response(
            {
                "status": "ok",
                "role": self.role or None,
                "draining": self.draining,
            }
        )

    async def h_drain(self, request: web.Request) -> web.Response:
        """The drain barrier, fake-shaped: admissions stop (503 +
        X-Engine-Draining on completions), in-flight work finishes,
        the engine deregisters. ?wait=true blocks until idle — 200 once
        drained, 202 while streams still run, the exact codes the
        rebalancer's drain phase keys on. Idempotent."""
        self.draining = True
        if request.query.get("wait", "").lower() in ("1", "true", "yes"):
            while self.running > 0:
                await asyncio.sleep(0.02)
        if self.running > 0:
            return web.json_response(
                {"status": "draining", "running": self.running}, status=202
            )
        await self._deregister()
        return web.json_response({"status": "drained", "running": 0})

    async def h_role(self, request: web.Request) -> web.Response:
        """Live role flip (docs/40-pool-rebalancing.md): adopt the new
        pool role, re-open admissions, re-register with the KV
        controller so the new role is advertised before the next
        scrape lands."""
        body = await request.json()
        role = body.get("role")
        if role not in mc.POOL_ROLE_VALUES:
            return web.json_response(
                {"error": {"message": (
                    f"role must be one of {sorted(mc.POOL_ROLE_VALUES)}"
                )}},
                status=400,
            )
        previous = self.role
        self.role = role
        self.draining = False
        self.role_flips += 1
        await self._register()
        return web.json_response(
            {"status": "ok", "role": role, "previous_role": previous or None}
        )

    async def _register(self) -> None:
        """Advertise this engine (and its role) to the KV controller.
        No-op without --kv-controller-url; failures are swallowed — a
        dead controller must never block serving (fail open)."""
        if not self.kv_controller_url or not self.self_url:
            return
        import aiohttp

        body: dict = {"url": self.self_url, "model": self.model}
        if self.role:
            body["role"] = self.role
        try:
            timeout = aiohttp.ClientTimeout(total=5)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                async with sess.post(
                    self.kv_controller_url + "/register", json=body
                ) as resp:
                    await resp.read()
        except Exception:
            pass

    async def _deregister(self) -> None:
        if not self.kv_controller_url or not self.self_url:
            return
        import aiohttp

        try:
            timeout = aiohttp.ClientTimeout(total=5)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                async with sess.post(
                    self.kv_controller_url + "/deregister",
                    json={"url": self.self_url},
                ) as resp:
                    await resp.read()
        except Exception:
            pass

    async def h_sleep(self, request: web.Request) -> web.Response:
        self.sleeping = True
        return web.json_response({"status": "ok", "sleeping": True})

    async def h_wake(self, request: web.Request) -> web.Response:
        self.sleeping = False
        return web.json_response({"status": "ok", "sleeping": False})

    async def h_is_sleeping(self, request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": self.sleeping})

    # -- assembly ----------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/v1/models", self.h_models)
        app.router.add_post("/v1/chat/completions", self.h_completion)
        app.router.add_post("/v1/completions", self.h_completion)
        app.router.add_post("/v1/audio/transcriptions", self.h_transcription)
        app.router.add_post("/v1/embeddings", self.h_embeddings)
        app.router.add_get("/metrics", self.h_metrics)
        app.router.add_get("/debug/stickiness", self.h_debug_stickiness)
        app.router.add_get("/health", self.h_health)
        app.router.add_post("/drain", self.h_drain)
        app.router.add_post("/role", self.h_role)
        app.router.add_post("/sleep", self.h_sleep)
        app.router.add_post("/wake_up", self.h_wake)
        app.router.add_get("/is_sleeping", self.h_is_sleeping)

        async def _startup(app: web.Application) -> None:
            await self._register()

        app.on_startup.append(_startup)
        return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="fake TPU engine for router testing")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9001)
    p.add_argument("--model", default="fake-model")
    p.add_argument("--tokens-per-sec", type=float, default=500.0)
    p.add_argument("--model-label", default="")
    p.add_argument("--self-url", default="",
                   help="this engine's advertised URL — arms the "
                        "stickiness audit's non_owner_delivery detection")
    p.add_argument("--no-request-log", action="store_true",
                   help="disable the per-request log (open-loop load "
                        "benches would grow it unboundedly)")
    p.add_argument("--seats", type=int, default=0,
                   help="concurrent decode seats (0 = unbounded); excess "
                        "requests queue FIFO — the load model behind the "
                        "peer-tier route-vs-migrate bench")
    p.add_argument("--prefill-tps", type=float, default=0.0,
                   help="cold-prompt prefill rate (tokens/s; 0 disables "
                        "the warm/cold prefill model)")
    p.add_argument("--peer-pull-tps", type=float, default=0.0,
                   help="owner-hinted peer-pull rate (tokens/s) — the "
                        "cheap alternative to a cold prefill")
    p.add_argument("--kv-bytes-per-token", type=float, default=0.0,
                   help="tpu:kv_bytes_per_token exported on /metrics so "
                        "priced route-vs-migrate can price migrations "
                        "against this fake")
    p.add_argument("--role", default="", choices=["", "prefill", "decode"],
                   help="disaggregated pool role: prefill-role engines "
                        "skip the seat gate (capacity = prefill_tps), "
                        "decode-role engines queue for seats; POST /role "
                        "flips it live")
    p.add_argument("--kv-controller-url", default="",
                   help="KV controller base URL — the engine registers "
                        "its URL+role on startup and after every flip")
    args = p.parse_args(argv)
    from ..utils.system import raise_fd_limit

    # the 10k-concurrent-stream bench holds thousands of sockets per fake
    # engine; the 1024 default soft limit severs them mid-stream
    raise_fd_limit()
    engine = FakeEngine(
        model=args.model,
        tokens_per_sec=args.tokens_per_sec,
        model_label=args.model_label,
        self_url=args.self_url,
        log_requests=not args.no_request_log,
        seats=args.seats,
        prefill_tps=args.prefill_tps,
        peer_pull_tps=args.peer_pull_tps,
        kv_bytes_per_token=args.kv_bytes_per_token,
        role=args.role,
        kv_controller_url=args.kv_controller_url,
    )
    web.run_app(engine.build_app(), host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
