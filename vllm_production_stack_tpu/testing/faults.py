"""Deterministic fault injection for the request-lifecycle chaos suite.

The reference stack proves robustness claims operationally (kill a pod,
watch the router); this module makes the same faults injectable in-process
and DETERMINISTIC, driven through the real aiohttp wire — no mocks on the
client side, the router talks TCP to a server that misbehaves on cue:

- **ChaosEngine** — a FakeEngine whose streaming path can kill the
  connection abruptly mid-stream (`kill_after_chunks`), die after reading
  the request but before sending headers (`kill_before_headers`), or turn
  slow-loris (`stall_after_chunks`: send N chunks then hold the connection
  open sending nothing). Faults are plain instance attributes the test
  flips; every triggered fault is appended to `faults_fired` for
  assertions.
- **black_hole()** — a listener that accepts TCP and never writes a byte
  (the partition shape: connect succeeds, the request vanishes).
- **dead_port()** — a port with no listener (connect refused: the only
  fault the pre-chaos stack handled).

tests/test_chaos.py drives these against the real router app and asserts
the invariant this layer exists for: every request completes, fails over,
or gets ONE clean 4xx/5xx — never hangs, never silently drops.
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
import uuid

from aiohttp import web

from .fake_engine import FakeEngine


class ChaosEngine(FakeEngine):
    """FakeEngine with injectable, deterministic wire-level faults."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # fault knobs — set directly from tests; None/False = healthy
        self.kill_before_headers = False  # die after reading the body
        self.kill_after_chunks: int | None = None  # abrupt close mid-stream
        self.stall_after_chunks: int | None = None  # slow-loris
        self.stall_release = asyncio.Event()  # un-stalls held connections
        self.draining = False  # mimic a draining real engine
        self.faults_fired: list[str] = []

    def _kill(self, request: web.Request, label: str) -> None:
        """Abrupt TCP teardown — the client sees a dropped connection, not
        a clean HTTP close (SO_LINGER-style RST is not portable; closing
        the transport mid-response is close enough on loopback)."""
        self.faults_fired.append(label)
        if request.transport is not None:
            request.transport.close()

    async def h_completion(self, request: web.Request) -> web.StreamResponse:
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining",
                           "type": "service_unavailable"}},
                status=503,
                headers={"X-Engine-Draining": "1"},
            )
        body = await request.json()
        if self.kill_before_headers:
            # the request reached the engine (it may have been processed) —
            # a correct router must NOT resend it to another endpoint
            self._kill(request, "kill_before_headers")
            raise ConnectionResetError("chaos: killed before headers")
        if self.kill_after_chunks is None and self.stall_after_chunks is None:
            # healthy path: FakeEngine semantics, same accounting
            request = _replay_body(request, body)
            return await super().h_completion(request)
        # faulting stream path (mirrors FakeEngine's chunk shape)
        self.total_requests += 1
        self.seen_request_log.append(
            {"path": request.path, "body": body, "t": time.time()}
        )
        is_chat = request.path.endswith("chat/completions")
        n = int(body.get("max_tokens") or self.default_tokens)
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        gap = 1.0 / self.tokens_per_sec
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"}
        )
        await resp.prepare(request)
        self.running += 1
        try:
            for i in range(n):
                if self.kill_after_chunks is not None and i >= self.kill_after_chunks:
                    self._kill(request, "kill_after_chunks")
                    return resp
                if (
                    self.stall_after_chunks is not None
                    and i >= self.stall_after_chunks
                ):
                    # slow-loris: hold the connection open, send nothing
                    self.faults_fired.append("stall")
                    await self.stall_release.wait()
                    return resp
                await asyncio.sleep(gap)
                delta = (
                    {"delta": {"content": f"tok{i} "}}
                    if is_chat
                    else {"text": f"tok{i} "}
                )
                chunk = {
                    "id": rid, "created": created,
                    "object": ("chat.completion.chunk" if is_chat
                               else "text_completion"),
                    "model": body.get("model", self.model),
                    "choices": [{"index": 0, **delta, "finish_reason": None}],
                }
                await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        except (ConnectionResetError, ConnectionError):
            return resp
        finally:
            self.running -= 1


class _ReplayRequest:
    """Minimal request view whose json() replays an already-read body (the
    chaos handler reads it to decide faults before delegating)."""

    def __init__(self, request: web.Request, body: dict):
        self._request = request
        self._body = body

    def __getattr__(self, name):
        return getattr(self._request, name)

    async def json(self):
        return self._body


def _replay_body(request: web.Request, body: dict) -> web.Request:
    return _ReplayRequest(request, body)  # type: ignore[return-value]


class hold_lock:
    """Context manager that HOLDS a threading lock for its whole scope —
    the deterministic deadlock-shape wedge (docs/37-flight-recorder.md):
    any loop that needs the lock (the hydration fetcher under the
    disk-tier lock, classically) blocks busy until the scope exits, and
    the thread-liveness watchdog must name it."""

    def __init__(self, lock):
        self.lock = lock

    def __enter__(self):
        self.lock.acquire()
        return self

    def __exit__(self, *exc):
        self.lock.release()
        return False


class frozen_step_loop:
    """Context manager that freezes an engine's step loop: ``engine.step``
    is wrapped to block on an event until the scope exits — the
    whole-engine wedge shape (collective stall / runaway compile under
    the engine lock). The step thread stops beating its heartbeat while
    frozen, so the watchdog must name thread=step."""

    def __init__(self, engine):
        self.engine = engine
        self._release = None

    def __enter__(self):
        import threading

        release = threading.Event()
        real_step = self.engine.step

        def frozen(*a, **kw):
            release.wait()
            return real_step(*a, **kw)

        self.engine.step = frozen
        self._release = (release, real_step)
        return self

    def __exit__(self, *exc):
        release, real_step = self._release
        self.engine.step = real_step
        release.set()  # unblock a step thread parked inside the wrapper
        return False


async def black_hole() -> tuple[asyncio.AbstractServer, int]:
    """A listener that accepts connections and never responds — the
    network-partition shape (connect succeeds; the request vanishes).
    Caller closes the returned server."""

    async def swallow(reader, writer):
        try:
            while await reader.read(65536):
                pass
        except Exception:
            pass

    server = await asyncio.start_server(swallow, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    return server, port


def dead_port() -> int:
    """A loopback port with nothing listening (connect refused)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
