"""Event-loop-safe JSON parsing.

`json.loads` of a request/response body runs on the aiohttp event loop
wherever it's called from a handler — for a multi-MB prompt payload that
is a multi-ms stall every concurrent stream shares (the bug class the
PR 2 review pass fixed by hand in the /kv/events handlers, and that
tpulint's `async-blocking` rule now flags mechanically).  This helper is
the sanctioned escape: small payloads parse inline (an executor hop
costs more than the parse), large ones hop to the default executor.
"""

from __future__ import annotations

import asyncio
import json

# Below this, the parse is cheaper than the executor round-trip; above,
# the loop stall dominates.  64 KiB ≈ a 16k-token prompt.
OFFLOAD_BYTES = 64 * 1024


async def loads_off_loop(raw: bytes | bytearray | str):
    """`json.loads(raw)`, hopped off the event loop when `raw` is large.

    Raises `json.JSONDecodeError` exactly like the inline form."""
    if len(raw) <= OFFLOAD_BYTES:
        # tpulint: allow(async-blocking) — sub-64KiB parse is cheaper than
        # the executor round-trip; large payloads take the branch below
        return json.loads(raw)
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, json.loads, raw)
