"""Process-level system knobs.

Reference parity: the router raises its file-descriptor soft limit to the
hard limit at startup (utils.py:132-147 `set_ulimit`) — a proxy holding
one upstream + one downstream socket per in-flight streaming request
exhausts the usual 1024 default long before it exhausts CPU.
"""

from __future__ import annotations

from .logging import init_logger

logger = init_logger(__name__)


def raise_fd_limit(target: int = 65535) -> int:
    """Raise RLIMIT_NOFILE's soft limit toward min(target, hard limit).
    Returns the resulting soft limit; never raises (serving with the old
    limit beats dying at boot on a locked-down kernel)."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(target, hard) if hard != resource.RLIM_INFINITY else target
        # some kernels report an infinite hard limit while the real
        # ceiling sits lower (macOS kern.maxfilesperproc class) — step
        # down instead of giving up, any raise beats the 1024 default
        while want > soft:
            try:
                resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
                logger.info(
                    "raised RLIMIT_NOFILE soft limit %d -> %d", soft, want
                )
                return want
            except (ValueError, OSError):
                want //= 2
        return soft
    except (ImportError, ValueError, OSError) as e:
        logger.warning("could not raise fd limit: %s", e)
        return -1


def jittered_interval(interval_s: float, jitter_frac: float) -> float:
    """A sleep interval jittered ±jitter_frac around interval_s — the ONE
    herd-avoidance policy shared by every periodic fleet tick (the KV
    event publisher and the fleet reporter): M replicas × E engines
    starting together must de-correlate instead of hitting a shared
    subscriber on synchronized ticks (docs/34-fleet-routing.md)."""
    if jitter_frac <= 0:
        return interval_s
    import random

    return interval_s * random.uniform(1.0 - jitter_frac, 1.0 + jitter_frac)
