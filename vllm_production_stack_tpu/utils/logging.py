"""Colored logger setup (reference: src/vllm_router/log.py)."""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[36m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def init_logger(name: str, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            _ColorFormatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger
