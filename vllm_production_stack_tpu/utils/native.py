"""ctypes bindings for the repo's native (C++) runtime components.

The reference stack's hot host-side paths are native (LMCache's token
hashing, the Go gateway pickers); this module is the TPU stack's equivalent
glue: small C++ shared libraries under csrc/, compiled on first use with the
system toolchain (no pybind11 in this image — plain `extern "C"` + ctypes),
each with a pure-Python fallback so the stack never hard-requires a
compiler at runtime.

Components:
  - kvhash: batch KV chain-hasher (csrc/kvhash.cpp) — one C call hashes a
    whole prompt's full blocks for the content-addressed prefix cache
    (engine/kv_cache.py) instead of one Python sha256 round-trip per block.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
import threading

import numpy as np

from .logging import init_logger

logger = init_logger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_CSRC = os.path.join(_REPO_ROOT, "csrc")
_LOCK = threading.Lock()
_KVHASH: ctypes.CDLL | None = None
_KVHASH_FAILED = False


def _build_dir() -> str | None:
    d = os.environ.get("VLLM_TPU_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), f"vllm-tpu-native-{os.getuid()}"
    )
    os.makedirs(d, mode=0o700, exist_ok=True)
    # refuse a cache dir we don't own: on a multi-user host an attacker could
    # pre-create the predictable path and plant a .so that CDLL would execute
    st = os.stat(d)
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        logger.warning(
            "native cache dir %s is not private to this user; "
            "refusing to load native libraries from it", d,
        )
        return None
    return d


def _compile(name: str) -> str | None:
    """g++ -O3 -shared csrc/<name>.cpp → cached .so; None if impossible.
    The cache key embeds a content hash of the source, so two checkouts
    sharing the per-uid cache dir can never load each other's binaries."""
    import hashlib

    src = os.path.join(_CSRC, f"{name}.cpp")
    if not os.path.exists(src):
        return None
    build_dir = _build_dir()
    if build_dir is None:
        return None
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(build_dir, f"lib{name}-{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(tmp, out)  # atomic under concurrent builders
        return out
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native %s build failed (%s); using Python fallback",
                       name, detail.strip()[:200])
        return None


def _load_kvhash() -> ctypes.CDLL | None:
    global _KVHASH, _KVHASH_FAILED
    if _KVHASH is not None or _KVHASH_FAILED:
        return _KVHASH
    with _LOCK:
        if _KVHASH is not None or _KVHASH_FAILED:
            return _KVHASH
        if sys.byteorder != "little":  # the C path reinterprets int64 bytes
            _KVHASH_FAILED = True
            return None
        path = _compile("kvhash")
        if path is None:
            _KVHASH_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.kvhash_chain.restype = ctypes.c_int64
            lib.kvhash_chain.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
        except OSError as e:
            logger.warning("native kvhash load failed (%s)", e)
            _KVHASH_FAILED = True
            return None
        _KVHASH = lib
        logger.info("native kvhash loaded from %s", path)
        return _KVHASH


def chain_hashes_native(
    parent: int, token_ids, block_size: int
) -> list[int] | None:
    """All full-block chain hashes of a prompt in one native call, byte-exact
    with kv_cache.chain_hash chaining. None if the native library is
    unavailable (callers fall back to the Python loop)."""
    lib = _load_kvhash()
    if lib is None:
        return None
    toks = np.ascontiguousarray(token_ids, dtype=np.int64)
    n_full = len(toks) // block_size
    if n_full <= 0:
        return []
    lo = np.empty(n_full, np.uint64)
    hi = np.empty(n_full, np.uint64)
    lib.kvhash_chain(
        ctypes.c_uint64(parent & 0xFFFFFFFFFFFFFFFF),
        ctypes.c_uint64((parent >> 64) & 0xFFFFFFFFFFFFFFFF),
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(toks)),
        ctypes.c_int64(block_size),
        lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return [int(lo[i]) | (int(hi[i]) << 64) for i in range(n_full)]
