"""Tokenizer wrapper: HF AutoTokenizer when a local checkpoint/tokenizer path
is configured, byte-level fallback otherwise (zero-egress environments and
tests can't download vocabularies)."""

from __future__ import annotations

import os

from .logging import init_logger

logger = init_logger(__name__)

_TOKENIZER_FILES = ("tokenizer.json", "tokenizer_config.json", "vocab.json")


class ByteTokenizer:
    """256 byte tokens + BOS/EOS/PAD. Deterministic, dependency-free."""

    BOS = 256
    EOS = 257
    PAD = 258
    vocab_size = 259

    bos_token_id = BOS
    eos_token_id = EOS
    pad_token_id = PAD

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.BOS] + ids if add_special_tokens else ids

    def decode(self, ids: list[int], skip_special_tokens: bool = True) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True, **_
    ) -> str:
        parts = [f"<|{m['role']}|>\n{_content_text(m)}\n" for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "".join(parts)


def _content_text(message: dict) -> str:
    content = message.get("content", "")
    if isinstance(content, list):  # multimodal parts; keep the text ones
        return "".join(
            p.get("text", "") for p in content if isinstance(p, dict)
        )
    return content or ""


_BYTE_DECODER: dict[str, int] | None = None


def _gpt2_byte_decoder() -> dict[str, int]:
    """Inverse of the GPT-2 bytes→unicode table byte-level BPE vocabularies
    are written in (each vocab char stands for exactly one byte). Cached —
    token_repr sits on the logprobs hot path."""
    global _BYTE_DECODER
    if _BYTE_DECODER is not None:
        return _BYTE_DECODER
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    _BYTE_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTE_DECODER


_SP_BYTE_RE = None  # compiled lazily: sentencepiece byte-fallback "<0xNN>"


class TokenizerWrapper:
    """Uniform interface over HF tokenizers and the byte fallback, with
    incremental detokenization for streaming."""

    def __init__(self, tokenizer_path: str | None = None):
        if tokenizer_path and self._is_dir_without_tokenizer(tokenizer_path):
            # weights-only checkpoint dir: serve token-ids with the byte
            # fallback rather than refusing to start. A mistyped/remote path
            # or broken tokenizer files still fail loudly below.
            logger.warning(
                "no tokenizer files (%s) under %s; using the byte fallback",
                "/".join(_TOKENIZER_FILES), tokenizer_path,
            )
            tokenizer_path = None
        if tokenizer_path:
            from transformers import AutoTokenizer

            self._tok = AutoTokenizer.from_pretrained(tokenizer_path)
        else:
            self._tok = ByteTokenizer()

    @staticmethod
    def _is_dir_without_tokenizer(path: str) -> bool:
        return os.path.isdir(path) and not any(
            os.path.exists(os.path.join(path, f)) for f in _TOKENIZER_FILES
        )

    @property
    def eos_token_id(self) -> int | None:
        return getattr(self._tok, "eos_token_id", None)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def _piece_family(self) -> str:
        """"bytelevel" (GPT-2/Llama-3-style Ġ vocab), "sp" (SentencePiece ▁
        vocab), or "plain". Detected once from the vocabulary — the family
        decides how a piece's chars map to content bytes."""
        fam = getattr(self, "_family", None)
        if fam is None:
            fam = "plain"
            get_vocab = getattr(self._tok, "get_vocab", None)
            if get_vocab is not None:
                for key in get_vocab():
                    if "Ġ" in key:
                        fam = "bytelevel"
                        break
                    if "▁" in key:
                        fam = "sp"
                        break
            self._family = fam
        return fam

    def token_repr(self, tid: int) -> tuple[str, bytes]:
        """(display string, content bytes) for ONE token id — the logprobs
        API surface. decode() of a single id is wrong for this (SentencePiece
        strips leading-space markers; partial UTF-8 decodes to nothing), and
        the piece's own UTF-8 is wrong too: byte-level-BPE chars are a byte
        alphabet (Ġ = 0x20) and SentencePiece ▁ is a marker — the OpenAI
        `bytes` field must carry the DECODED content bytes so concatenating
        them reconstructs the output text."""
        tid = int(tid)
        tok = self._tok
        if hasattr(tok, "convert_ids_to_tokens"):
            piece = tok.convert_ids_to_tokens(tid)
            if piece is None:
                return "", b""
            fam = self._piece_family()
            if fam == "bytelevel":
                bd = _gpt2_byte_decoder()
                if all(c in bd for c in piece):
                    raw = bytes(bd[c] for c in piece)
                    return raw.decode("utf-8", errors="replace"), raw
                # special token (<|eot_id|> etc.): literal text
                return piece, piece.encode("utf-8")
            if fam == "sp":
                global _SP_BYTE_RE
                if _SP_BYTE_RE is None:
                    import re

                    _SP_BYTE_RE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")
                m = _SP_BYTE_RE.match(piece)
                if m:  # sentencepiece byte-fallback token = one raw byte
                    raw = bytes([int(m.group(1), 16)])
                    return raw.decode("utf-8", errors="replace"), raw
                s = piece.replace("\u2581", " ")
                return s, s.encode("utf-8")
            s = (
                piece.replace("\u2581", " ")
                .replace("\u0120", " ")
                .replace("\u010a", "\n")
            )
            return s, s.encode("utf-8")
        if 0 <= tid < 256:
            s = chr(tid) if 32 <= tid < 127 else f"<0x{tid:02x}>"
            return s, bytes([tid])
        return "", b""

    def chat_prompt(self, messages: list[dict]) -> str:
        try:
            out = self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
            if isinstance(out, str):
                return out
        except Exception:
            pass
        return ByteTokenizer().apply_chat_template(messages)


def hashing_tokenizer(spec: str | None) -> TokenizerWrapper | None:
    """Tokenizer for KV chain hashing from a CLI/config spec: an HF
    checkpoint/tokenizer dir, or "byte" for the byte fallback. None/""
    means text cannot be hashed locally (callers fall back to engine-side
    probes). The router's embedded index and the KV controller MUST resolve
    specs through this one function — divergent resolution would hash the
    same prompt differently on the two ends of the KV-event protocol."""
    if not spec:
        return None
    return TokenizerWrapper(None if spec == "byte" else spec)


class IncrementalDetokenizer:
    """Streams text deltas from a growing token-id list, holding back bytes
    that may be a partial multi-byte character / merged token.

    Offset-window scheme (per-push cost bounded by the held-back tail, not the
    full output): only ids[prefix_offset:] are ever re-decoded; once a stable
    delta is emitted the window advances."""

    def __init__(self, tokenizer: TokenizerWrapper):
        self._tok = tokenizer
        self._ids: list[int] = []
        self._prefix_offset = 0  # start of the re-decode window
        self._read_offset = 0  # ids before this are already emitted
        self._emitted = ""

    def push(self, token_ids: list[int]) -> str:
        self._ids.extend(token_ids)
        prefix = self._tok.decode(self._ids[self._prefix_offset : self._read_offset])
        full = self._tok.decode(self._ids[self._prefix_offset :])
        if full.endswith("�"):  # partial utf-8 tail; wait for more tokens
            return ""
        if len(full) <= len(prefix):
            return ""
        delta = full[len(prefix) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        self._emitted += delta
        return delta

    @property
    def text(self) -> str:
        return self._emitted
