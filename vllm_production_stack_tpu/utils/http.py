"""Shared aiohttp client plumbing."""

from __future__ import annotations

import asyncio

import aiohttp


class LazyClientSession:
    """One long-lived aiohttp.ClientSession built on first use, under a
    lock: concurrent FIRST callers must not each construct a session — all
    but one would leak its connector. Hot paths (router KV lookups, KV
    controller fan-out probes) share one instance so per-request
    session+connection churn never taxes latency or file descriptors."""

    def __init__(self, **session_kwargs):
        self._kwargs = session_kwargs
        self._lock = asyncio.Lock()
        self.session: aiohttp.ClientSession | None = None

    async def get(self) -> aiohttp.ClientSession:
        if self.session is None or self.session.closed:
            async with self._lock:
                if self.session is None or self.session.closed:
                    self.session = aiohttp.ClientSession(**self._kwargs)
        return self.session

    async def close(self) -> None:
        if self.session is not None and not self.session.closed:
            await self.session.close()
