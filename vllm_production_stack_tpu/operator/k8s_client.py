"""Minimal Kubernetes REST client (aiohttp, no external k8s SDK).

The reference operator is Go/kubebuilder on controller-runtime; this image
has no Go toolchain and no kubernetes python package, so the operator talks
to the API server over its plain REST surface directly — which also makes it
trivially testable against an in-process fake API server (the envtest
strategy the reference uses, suite_test.go:52-60, without the binary).

In-cluster config: service-account token + CA from the standard paths;
tests construct the client with an explicit base_url.
"""

from __future__ import annotations

import os
import ssl

import aiohttp

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"K8s API {status}: {body[:200]}")
        self.status = status


class K8sClient:
    def __init__(self, base_url: str | None = None, token: str | None = None,
                 namespace: str = "default", ssl_ctx=None):
        if base_url is None:  # in-cluster
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
            with open(f"{SA_DIR}/namespace") as f:
                namespace = f.read().strip()
            ssl_ctx = ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self._token = token
        self._ssl = ssl_ctx
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            headers = {}
            if self._token:
                headers["Authorization"] = f"Bearer {self._token}"
            self._session = aiohttp.ClientSession(
                headers=headers, timeout=aiohttp.ClientTimeout(total=30)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _request(self, method: str, path: str, body=None,
                       content_type: str = "application/json"):
        kwargs: dict = {"ssl": self._ssl}
        if body is not None:
            kwargs["json"] = body
            kwargs["headers"] = {"Content-Type": content_type}
        async with self._sess().request(
            method, self.base_url + path, **kwargs
        ) as resp:
            if resp.status == 404:
                return None
            if resp.status >= 400:
                raise ApiError(resp.status, await resp.text())
            return await resp.json()

    # -- typed paths -------------------------------------------------------

    def _core(self, kind_plural: str, name: str = "") -> str:
        p = f"/api/v1/namespaces/{self.namespace}/{kind_plural}"
        return f"{p}/{name}" if name else p

    def _apps(self, kind_plural: str, name: str = "") -> str:
        p = f"/apis/apps/v1/namespaces/{self.namespace}/{kind_plural}"
        return f"{p}/{name}" if name else p

    def _crd(self, plural: str, name: str = "") -> str:
        p = (
            "/apis/production-stack.tpu.ai/v1alpha1/namespaces/"
            f"{self.namespace}/{plural}"
        )
        return f"{p}/{name}" if name else p

    # -- operations --------------------------------------------------------

    async def get(self, path: str):
        return await self._request("GET", path)

    async def list(self, path: str, label_selector: str | None = None):
        if label_selector:
            from urllib.parse import quote

            path = f"{path}?labelSelector={quote(label_selector)}"
        out = await self._request("GET", path)
        return (out or {}).get("items", [])

    async def list_raw(self, path: str) -> dict:
        """Full list response including metadata.resourceVersion — the
        start point for a watch."""
        return await self._request("GET", path) or {}

    async def watch(self, path: str, resource_version: str | None = None,
                    timeout_s: float = 300.0):
        """Streaming watch (the list+watch half of controller-runtime's
        informers, operator/cmd/main.go:58-266): yields
        {"type": ADDED|MODIFIED|DELETED|BOOKMARK, "object": {...}} events
        as JSON lines arrive. Raises ApiError(410) when the
        resourceVersion is too old — caller re-lists and re-watches."""
        import json

        from urllib.parse import quote

        params = "?watch=1&allowWatchBookmarks=true"
        if resource_version:
            params += f"&resourceVersion={quote(str(resource_version))}"
        async with self._sess().get(
            self.base_url + path + params,
            ssl=self._ssl,
            timeout=aiohttp.ClientTimeout(total=None, sock_read=timeout_s),
        ) as resp:
            if resp.status >= 400:
                raise ApiError(resp.status, await resp.text())
            # incremental line buffer: resp.content's line iterator caps a
            # line at the 64KB reader limit, and real watch events (big
            # pod specs, managedFields) routinely exceed it
            buf = bytearray()
            async for chunk in resp.content.iter_any():
                buf.extend(chunk)
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line = bytes(buf[:nl]).strip()
                    del buf[: nl + 1]
                    if not line:
                        continue
                    # tpulint: allow(async-blocking) — one watch event per
                    # line, KB-scale by apiserver construction
                    event = json.loads(line)
                    if event.get("type") == "ERROR":
                        status = event.get("object", {})
                        raise ApiError(
                            status.get("code", 500),
                            str(status.get("message")),
                        )
                    yield event

    async def create(self, path: str, obj: dict):
        return await self._request("POST", path, obj)

    async def replace(self, path: str, obj: dict):
        return await self._request("PUT", path, obj)

    async def delete(self, path: str):
        return await self._request("DELETE", path)

    async def patch_status(self, path: str, status: dict):
        return await self._request(
            "PATCH", path + "/status", {"status": status},
            content_type="application/merge-patch+json",
        )

    async def apply(self, path_fn, obj: dict) -> dict:
        """Create-or-replace by name (server-side apply equivalent for the
        few object kinds the operator manages)."""
        name = obj["metadata"]["name"]
        existing = await self.get(path_fn(name))
        if existing is None:
            return await self.create(path_fn(""), obj) or obj
        obj = {**obj}
        obj["metadata"] = {
            **obj["metadata"],
            "resourceVersion": existing["metadata"].get("resourceVersion"),
        }
        if obj.get("kind") == "Service":
            # clusterIP(s) are apiserver-assigned and immutable: a replace
            # that omits them is a 422 on a real apiserver
            for field in ("clusterIP", "clusterIPs"):
                if field in existing.get("spec", {}):
                    obj.setdefault("spec", {})[field] = existing["spec"][field]
        return await self.replace(path_fn(name), obj) or obj

    # convenience bound path builders
    def deployments(self, name: str = "") -> str:
        return self._apps("deployments", name)

    def services(self, name: str = "") -> str:
        return self._core("services", name)

    def pvcs(self, name: str = "") -> str:
        return self._core("persistentvolumeclaims", name)

    def pods(self, name: str = "") -> str:
        return self._core("pods", name)

    def crs(self, plural: str, name: str = "") -> str:
        return self._crd(plural, name)

    def leases(self, name: str = "") -> str:
        p = (
            "/apis/coordination.k8s.io/v1/namespaces/"
            f"{self.namespace}/leases"
        )
        return f"{p}/{name}" if name else p
