"""Operator manager: watch-driven reconcile with Lease leader election.

The reference uses controller-runtime's informer caches + leader election
(operator/cmd/main.go:58-266). Same shape here on the minimal REST client:
per-kind list+watch loops (reconcile on every ADDED/MODIFIED event, re-list
on 410 Gone), a Pod watch that re-reconciles LoraAdapters on readiness
transitions, a periodic level-triggered resync as the convergence backstop,
and coordination.k8s.io/v1 Lease leadership so replicas don't fight over
patches — standbys block until the lease expires, and a leader that loses
its lease stops reconciling and exits (pod restart returns it as a standby).

Run (in-cluster): python -m vllm_production_stack_tpu.operator.manager
"""

from __future__ import annotations

import argparse
import asyncio
import datetime
import os
import socket

import aiohttp

from ..utils.logging import init_logger
from .controllers import (
    CacheServerReconciler,
    LoraAdapterReconciler,
    TPURouterReconciler,
    TPURuntimeReconciler,
)
from .k8s_client import ApiError, K8sClient

logger = init_logger(__name__)


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _micro_time(dt: datetime.datetime) -> str:
    """Kubernetes MicroTime format."""
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


class LostLeadership(Exception):
    pass


class LeaderElector:
    """Lease-based leader election (the reference enables the
    controller-runtime equivalent via --leader-elect, cmd/main.go)."""

    def __init__(self, client: K8sClient, lease_name: str = "tpu-stack-operator",
                 identity: str | None = None, lease_duration_s: float = 15.0):
        self.c = client
        self.lease_name = lease_name
        self.identity = identity or f"{socket.gethostname()}_{os.getpid()}"
        self.duration_s = lease_duration_s
        # client-go semantics: expiry is timed from when THIS process last
        # OBSERVED the lease record change (local monotonic clock) — never
        # by comparing the holder's renewTime against our wall clock, which
        # would let a skewed standby steal a live lease (split brain)
        self._observed_record: str | None = None
        self._observed_at: float = 0.0

    def _fresh_lease(self) -> dict:
        now = _micro_time(_now())
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": max(1, int(self.duration_s)),
                "acquireTime": now,
                "renewTime": now,
                "leaseTransitions": 0,
            },
        }

    async def try_acquire(self) -> bool:
        """One acquisition/renewal attempt. True iff we hold the lease
        afterwards. Conflicts (another replica raced us) return False."""
        path = self.c.leases(self.lease_name)
        try:
            lease = await self.c.get(path)
        except ApiError:
            return False
        if lease is None:
            try:
                await self.c.create(self.c.leases(), self._fresh_lease())
                return True
            except ApiError:
                return False  # another replica created it first
        import time

        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        duration = spec.get("leaseDurationSeconds", int(self.duration_s))
        record = f"{holder}|{spec.get('renewTime')}"
        now_mono = time.monotonic()
        if record != self._observed_record:
            # the record moved: restart OUR expiry clock (a first sighting
            # also lands here — a standby must watch an unchanged record
            # for a full lease duration before calling it dead)
            self._observed_record = record
            self._observed_at = now_mono
        expired = (now_mono - self._observed_at) > duration
        if holder != self.identity and not expired:
            return False  # live leader elsewhere
        spec = {
            **spec,
            "holderIdentity": self.identity,
            "leaseDurationSeconds": max(1, int(self.duration_s)),
            "renewTime": _micro_time(_now()),
        }
        if holder != self.identity:
            spec["acquireTime"] = spec["renewTime"]
            spec["leaseTransitions"] = spec.get("leaseTransitions", 0) + 1
        lease["spec"] = spec
        try:
            await self.c.replace(path, lease)
            return True
        except ApiError:
            return False  # resourceVersion conflict: raced another replica

    async def acquire(self, poll_s: float | None = None) -> None:
        """Block until this replica is the leader."""
        poll = poll_s if poll_s is not None else self.duration_s / 3
        while not await self.try_acquire():
            await asyncio.sleep(poll)
        logger.info("leadership acquired by %s", self.identity)

    async def renew_loop(self) -> None:
        """Renew forever; raises LostLeadership only when the lease is
        DEMONSTRABLY gone — another holder took it, or our last successful
        renewal is older than the lease duration. Transient apiserver
        errors within the lease window just retry (controller-runtime
        semantics: abdicating early creates an avoidable leaderless
        window)."""
        import time

        last_renew = time.monotonic()
        while True:
            await asyncio.sleep(self.duration_s / 3)
            if await self.try_acquire():
                last_renew = time.monotonic()
                continue
            try:
                lease = await self.c.get(self.c.leases(self.lease_name))
                holder = (lease or {}).get("spec", {}).get("holderIdentity")
                if holder and holder != self.identity:
                    raise LostLeadership(self.identity)  # usurped
            except ApiError:
                pass  # apiserver unavailable: fall through to the deadline
            if time.monotonic() - last_renew > self.duration_s:
                raise LostLeadership(self.identity)


class OperatorManager:
    def __init__(self, client: K8sClient, engine_port: int = 8000,
                 resync_s: float = 300.0):
        self.c = client
        self._engine_port = engine_port
        self.resync_s = resync_s
        self._http: aiohttp.ClientSession | None = None
        self._reconcilers: list | None = None
        self.is_leader = False
        self.reconcile_total = 0
        self.reconcile_errors = 0

    def build_health_app(self):
        """/healthz, /readyz (ready = leading), /metrics — the reference
        manager's probe + metrics surface (cmd/main.go:58-266)."""
        from aiohttp import web

        async def healthz(request):
            return web.json_response({"status": "ok"})

        async def readyz(request):
            if self.is_leader:
                return web.json_response({"status": "leading"})
            return web.json_response({"status": "standby"}, status=503)

        async def metrics(request):
            return web.Response(text=(
                "# TYPE tpu_operator_reconcile_total counter\n"
                f"tpu_operator_reconcile_total {self.reconcile_total}\n"
                "# TYPE tpu_operator_reconcile_errors_total counter\n"
                f"tpu_operator_reconcile_errors_total {self.reconcile_errors}\n"
                "# TYPE tpu_operator_is_leader gauge\n"
                f"tpu_operator_is_leader {int(self.is_leader)}\n"
            ))

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        app.router.add_get("/readyz", readyz)
        app.router.add_get("/metrics", metrics)
        return app

    @property
    def http(self) -> aiohttp.ClientSession:
        # lazy: ClientSession needs a running event loop, and main()
        # constructs the manager before asyncio.run()
        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15)
            )
        return self._http

    @property
    def reconcilers(self) -> list:
        if self._reconcilers is None:
            self._reconcilers = [
                TPURuntimeReconciler(self.c),
                TPURouterReconciler(self.c),
                CacheServerReconciler(self.c),
                LoraAdapterReconciler(self.c, self.http, self._engine_port),
            ]
        return self._reconcilers

    async def _reconcile_one(self, rec, cr: dict) -> bool:
        try:
            await rec.reconcile(cr)
            self.reconcile_total += 1
            return True
        except Exception:
            self.reconcile_errors += 1
            logger.exception(
                "reconcile %s/%s failed", rec.plural,
                cr.get("metadata", {}).get("name"),
            )
            return False

    async def reconcile_all(self) -> int:
        """One level-triggered pass over every CR of every kind; returns CRs
        reconciled. Errors are per-CR: one bad object must not wedge the
        others."""
        n = 0
        for rec in self.reconcilers:
            try:
                crs = await self.c.list(self.c.crs(rec.plural))
            except Exception as e:
                logger.warning("listing %s failed: %s", rec.plural, e)
                continue
            for cr in crs:
                if await self._reconcile_one(rec, cr):
                    n += 1
        return n

    # -- watch loops -------------------------------------------------------

    async def watch_kind(self, rec) -> None:
        """list+watch one CR kind forever: reconcile everything once, then
        reconcile each object as events arrive. 410 Gone or a dropped
        connection restarts from a fresh list (informer semantics)."""
        path = self.c.crs(rec.plural)
        while True:
            try:
                listing = await self.c.list_raw(path)
                rv = listing.get("metadata", {}).get("resourceVersion")
                for cr in listing.get("items", []):
                    await self._reconcile_one(rec, cr)
                async for event in self.c.watch(path, resource_version=rv):
                    etype = event.get("type")
                    obj = event.get("object", {})
                    rv = obj.get("metadata", {}).get("resourceVersion", rv)
                    if etype == "BOOKMARK":
                        continue
                    if etype in ("ADDED", "MODIFIED"):
                        await self._reconcile_one(rec, obj)
                    # DELETED needs no action for owned resources (GC via
                    # ownerReferences); LoraAdapter deletes arrive as
                    # MODIFIED with deletionTimestamp (finalizer) first
            except asyncio.CancelledError:
                raise
            except ApiError as e:
                if e.status != 410:  # 410 Gone: just re-list
                    logger.warning("watch %s error: %s", rec.plural, e)
                    await asyncio.sleep(1.0)
            except Exception as e:
                logger.warning("watch %s dropped: %s", rec.plural, e)
                await asyncio.sleep(1.0)

    @staticmethod
    def _pod_lora_state(pod: dict):
        """The tuple whose change makes a pod event LoRA-relevant: only
        model-labeled engine pods, only readiness/address transitions —
        status heartbeats and unrelated pods must not fan out into
        adapter reconciles (reference filters its Pod watch the same way,
        loraadapter_controller.go:235-275)."""
        if "model" not in pod.get("metadata", {}).get("labels", {}):
            return None
        conds = {
            c.get("type"): c.get("status")
            for c in pod.get("status", {}).get("conditions", [])
        }
        return (
            conds.get("Ready") == "True",
            pod.get("status", {}).get("podIP"),
        )

    async def watch_pods(self) -> None:
        """Pod readiness transitions re-trigger LoraAdapter reconciles."""
        lora = self.reconcilers[-1]
        path = self.c.pods()
        seen: dict[str, tuple] = {}
        while True:
            try:
                listing = await self.c.list_raw(path)
                rv = listing.get("metadata", {}).get("resourceVersion")
                seen = {
                    p["metadata"]["name"]: st
                    for p in listing.get("items", [])
                    if (st := self._pod_lora_state(p)) is not None
                }
                async for event in self.c.watch(path, resource_version=rv):
                    etype = event.get("type")
                    pod = event.get("object", {})
                    name = pod.get("metadata", {}).get("name")
                    if etype == "DELETED":
                        relevant = seen.pop(name, None) is not None
                    elif etype in ("ADDED", "MODIFIED"):
                        state = self._pod_lora_state(pod)
                        relevant = state is not None and \
                            seen.get(name) != state
                        if state is not None:
                            seen[name] = state
                    else:
                        relevant = False
                    if not relevant:
                        continue
                    for cr in await self.c.list(self.c.crs(lora.plural)):
                        await self._reconcile_one(lora, cr)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning("pod watch dropped: %s", e)
                await asyncio.sleep(1.0)

    async def resync_loop(self) -> None:
        while True:
            await asyncio.sleep(self.resync_s)
            await self.reconcile_all()

    # -- lifecycle ---------------------------------------------------------

    async def run(self, elector: LeaderElector | None = None) -> None:
        """Acquire leadership, then run all watch loops until leadership is
        lost (raises LostLeadership) or cancelled."""
        if elector is None:
            elector = LeaderElector(self.c)
        await elector.acquire()
        self.is_leader = True
        tasks = [
            asyncio.create_task(self.watch_kind(rec))
            for rec in self.reconcilers
        ]
        tasks.append(asyncio.create_task(self.watch_pods()))
        tasks.append(asyncio.create_task(self.resync_loop()))
        renew = asyncio.create_task(elector.renew_loop())
        try:
            await renew  # raises LostLeadership (or CancelledError)
        finally:
            self.is_leader = False
            renew.cancel()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await self.close()

    async def close(self) -> None:
        if self._http is not None and not self._http.closed:
            await self._http.close()
        await self.c.close()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="TPU stack operator")
    p.add_argument("--engine-port", type=int, default=8000)
    p.add_argument("--resync", type=float, default=300.0,
                   help="level-triggered full-resync interval (s)")
    p.add_argument("--lease-name", default="tpu-stack-operator")
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--api-server", default=None,
                   help="API server URL (default: in-cluster config)")
    p.add_argument("--namespace", default="default")
    p.add_argument("--health-port", type=int, default=8081,
                   help="/healthz /readyz /metrics port (0 disables)")
    args = p.parse_args(argv)
    client = (
        K8sClient(args.api_server, namespace=args.namespace)
        if args.api_server
        else K8sClient()
    )
    mgr = OperatorManager(client, args.engine_port, resync_s=args.resync)
    elector = LeaderElector(
        client, lease_name=args.lease_name,
        lease_duration_s=args.lease_duration,
    )

    async def amain():
        runner = None
        if args.health_port:
            from aiohttp import web

            runner = web.AppRunner(mgr.build_health_app())
            await runner.setup()
            await web.TCPSite(runner, "0.0.0.0", args.health_port).start()
        try:
            await mgr.run(elector)
        finally:
            if runner is not None:
                await runner.cleanup()

    try:
        asyncio.run(amain())
    except LostLeadership:
        # exit non-zero: the Deployment restarts us as a standby
        raise SystemExit(2)


if __name__ == "__main__":
    main()
