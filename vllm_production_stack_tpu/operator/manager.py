"""Operator manager: periodic reconcile loops over the four CRDs.

The reference uses controller-runtime's watch-driven manager with leader
election (operator/cmd/main.go:58-266); this manager polls CR lists on an
interval — level-triggered reconciliation gives the same convergence
guarantees at small-cluster scale without a watch cache, and keeps the
operator runnable against any API server the minimal REST client can reach.

Run (in-cluster): python -m vllm_production_stack_tpu.operator.manager
"""

from __future__ import annotations

import argparse
import asyncio

import aiohttp

from ..utils.logging import init_logger
from .controllers import (
    CacheServerReconciler,
    LoraAdapterReconciler,
    TPURouterReconciler,
    TPURuntimeReconciler,
)
from .k8s_client import K8sClient

logger = init_logger(__name__)


class OperatorManager:
    def __init__(self, client: K8sClient, engine_port: int = 8000):
        self.c = client
        self._engine_port = engine_port
        self._http: aiohttp.ClientSession | None = None
        self._reconcilers: list | None = None

    @property
    def http(self) -> aiohttp.ClientSession:
        # lazy: ClientSession needs a running event loop, and main()
        # constructs the manager before asyncio.run()
        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15)
            )
        return self._http

    @property
    def reconcilers(self) -> list:
        if self._reconcilers is None:
            self._reconcilers = [
                TPURuntimeReconciler(self.c),
                TPURouterReconciler(self.c),
                CacheServerReconciler(self.c),
                LoraAdapterReconciler(self.c, self.http, self._engine_port),
            ]
        return self._reconcilers

    async def reconcile_all(self) -> int:
        """One pass over every CR of every kind; returns CRs reconciled.
        Errors are per-CR: one bad object must not wedge the others."""
        n = 0
        for rec in self.reconcilers:
            try:
                crs = await self.c.list(self.c.crs(rec.plural))
            except Exception as e:
                logger.warning("listing %s failed: %s", rec.plural, e)
                continue
            for cr in crs:
                try:
                    await rec.reconcile(cr)
                    n += 1
                except Exception:
                    logger.exception(
                        "reconcile %s/%s failed", rec.plural,
                        cr["metadata"]["name"],
                    )
        return n

    async def run(self, interval_s: float = 10.0) -> None:
        logger.info("operator manager started (interval %.0fs)", interval_s)
        try:
            while True:
                await self.reconcile_all()
                await asyncio.sleep(interval_s)
        finally:
            await self.close()

    async def close(self) -> None:
        if self._http is not None and not self._http.closed:
            await self._http.close()
        await self.c.close()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="TPU stack operator")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--engine-port", type=int, default=8000)
    p.add_argument("--api-server", default=None,
                   help="API server URL (default: in-cluster config)")
    p.add_argument("--namespace", default="default")
    args = p.parse_args(argv)
    client = (
        K8sClient(args.api_server, namespace=args.namespace)
        if args.api_server
        else K8sClient()
    )
    asyncio.run(OperatorManager(client, args.engine_port).run(args.interval))


if __name__ == "__main__":
    main()
