"""Desired-state builders: CR spec → Kubernetes objects.

The reference's equivalents are deploymentForVLLMRuntime
(vllmruntime_controller.go:190-523 — builds the full `vllm serve` arg list
and LMCache env) and the router/cacheserver builders; here the args target
the TPU engine/router CLIs and google.com/tpu resources.
"""

from __future__ import annotations

import re


def label_safe(value: str) -> str:
    """Kubernetes label values: [A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?,
    <= 63 chars. Served-model names like 'org/model' need sanitizing."""
    v = re.sub(r"[^A-Za-z0-9_.-]", "-", value)[:63]
    return v.strip("-_.") or "model"


def _meta(name: str, owner: dict, extra_labels: dict | None = None) -> dict:
    labels = {
        "app.kubernetes.io/part-of": "tpu-production-stack",
        "app.kubernetes.io/managed-by": "tpu-stack-operator",
        **(extra_labels or {}),
    }
    return {
        "name": name,
        "labels": labels,
        "ownerReferences": [{
            "apiVersion": owner["apiVersion"],
            "kind": owner["kind"],
            "name": owner["metadata"]["name"],
            "uid": owner["metadata"].get("uid", ""),
            "controller": True,
        }],
    }


def engine_args(spec: dict) -> list[str]:
    """TPURuntime spec → engine server argv (reference builds `vllm serve`
    args the same way, vllmruntime_controller.go:228-286)."""
    model = spec.get("model", {})
    tpu = spec.get("tpuConfig", {})
    args = [
        "-m", "vllm_production_stack_tpu.engine.server",
        "--model", model.get("modelURL", "tiny-llama"),
        "--port", str(tpu.get("port", 8000)),
    ]
    if model.get("servedModelName"):
        args += ["--served-model-name", model["servedModelName"]]
    if model.get("maxModelLen"):
        args += ["--max-model-len", str(model["maxModelLen"])]
    if model.get("dtype"):
        args += ["--dtype", model["dtype"]]
    if model.get("quantization"):
        args += ["--quantization", str(model["quantization"])]
    if tpu.get("tensorParallelSize"):
        args += ["--tensor-parallel-size", str(tpu["tensorParallelSize"])]
    if tpu.get("maxNumSeqs"):
        args += ["--max-num-seqs", str(tpu["maxNumSeqs"])]
    if tpu.get("maxLoras"):
        args += ["--max-loras", str(tpu["maxLoras"])]
    if tpu.get("numHostBlocks"):
        args += ["--num-host-blocks", str(tpu["numHostBlocks"])]
    if tpu.get("sequenceParallelSize"):
        args += ["--sequence-parallel-size", str(tpu["sequenceParallelSize"])]
    if tpu.get("expertParallelSize"):
        args += ["--expert-parallel-size", str(tpu["expertParallelSize"])]
    if tpu.get("kvCacheDtype"):
        args += ["--kv-cache-dtype", str(tpu["kvCacheDtype"])]
    if tpu.get("numSpeculativeTokens"):
        args += ["--num-speculative-tokens", str(tpu["numSpeculativeTokens"])]
    if tpu.get("speculativeConfig"):
        args += ["--speculative-config", str(tpu["speculativeConfig"])]
    if tpu.get("draftModel"):
        args += ["--draft-model", str(tpu["draftModel"])]
    if tpu.get("decodeWindow"):
        args += ["--decode-window", str(tpu["decodeWindow"])]
    if tpu.get("enablePrefixCaching") is False:
        args += ["--no-enable-prefix-caching"]
    # KV tier config (the reference's LMCacheConfig block: CPU offload size
    # in GiB + remote server URL, vllmruntime_controller.go:337-374)
    kv = spec.get("kvConfig", {})
    if kv.get("hostKvGib"):
        args += ["--host-kv-gib", str(kv["hostKvGib"])]
    if kv.get("diskKvGib"):
        # dir defaults like the helm template: a bare diskKvGib must turn
        # the tier ON, not silently no-op behind the engine's dir+gib gate
        args += ["--disk-kv-dir", str(kv.get("diskKvDir") or "/data/kv-cache")]
        args += ["--disk-kv-gib", str(kv["diskKvGib"])]
    elif kv.get("diskKvDir"):
        args += ["--disk-kv-dir", str(kv["diskKvDir"])]
    if kv.get("remoteKvUrl"):
        args += ["--remote-kv-url", str(kv["remoteKvUrl"])]
    args += [str(a) for a in tpu.get("extraArgs", [])]
    return args


def deployment_for_runtime(cr: dict) -> dict:
    spec = cr["spec"]
    name = cr["metadata"]["name"]
    tpu = spec.get("tpuConfig", {})
    image = spec.get("image", {})
    model_label = spec.get("modelLabel", "")
    pod_labels = {
        "app": "tpu-stack-engine",
        "model": label_safe(
            spec.get("model", {}).get("servedModelName", name)
        ),
        "tpuruntime": name,
    }
    if model_label:
        pod_labels["model-label"] = model_label

    container: dict = {
        "name": "engine",
        "image": f"{image.get('repository', 'tpu-stack-engine')}:"
                 f"{image.get('tag', 'latest')}",
        "command": ["python"],
        "args": engine_args(spec),
        "ports": [{"containerPort": tpu.get("port", 8000), "name": "http"}],
        "startupProbe": {
            "httpGet": {"path": "/health", "port": "http"},
            "initialDelaySeconds": 30, "periodSeconds": 10,
            "failureThreshold": 120,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": "http"},
            "periodSeconds": 10, "failureThreshold": 3,
        },
    }
    env = list(tpu.get("env", []))
    hf_secret = spec.get("model", {}).get("hfTokenSecret")
    if hf_secret:
        env.append({
            "name": "HF_TOKEN",
            "valueFrom": {"secretKeyRef": {"name": hf_secret, "key": "token"}},
        })
    kv = spec.get("kvTransferConfig", {})
    if kv.get("kvControllerURL"):
        # the engine self-registers with the KV controller at startup
        # (engine/server.py reads these — the LMCACHE_CONTROLLER_URL
        # equivalent, deployment-vllm-multi.yaml:324-339)
        env.append({"name": "KV_CONTROLLER_URL",
                    "value": kv["kvControllerURL"]})
        env.append({"name": "POD_IP", "valueFrom": {
            "fieldRef": {"fieldPath": "status.podIP"}}})
        env.append({"name": "ENGINE_PORT", "value": str(tpu.get("port", 8000))})
    if env:
        container["env"] = env

    resources = dict(spec.get("resources", {}))
    if tpu.get("requestTPU"):
        n = str(tpu["requestTPU"])
        resources.setdefault("requests", {})["google.com/tpu"] = n
        resources.setdefault("limits", {})["google.com/tpu"] = n
    if resources:
        container["resources"] = resources

    pod_spec: dict = {"containers": [container]}
    if tpu.get("tpuAccelerator"):
        sel = {"cloud.google.com/gke-tpu-accelerator": tpu["tpuAccelerator"]}
        if tpu.get("tpuTopology"):
            sel["cloud.google.com/gke-tpu-topology"] = tpu["tpuTopology"]
        pod_spec["nodeSelector"] = sel
    if spec.get("storage", {}).get("pvcStorage"):
        container["volumeMounts"] = [{"name": "weights", "mountPath": "/data"}]
        pod_spec["volumes"] = [{
            "name": "weights",
            "persistentVolumeClaim": {"claimName": f"{name}-pvc"},
        }]

    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"{name}-engine", cr, pod_labels),
        "spec": {
            "replicas": spec.get("replicas", 1),
            "selector": {"matchLabels": {"tpuruntime": name}},
            "template": {"metadata": {"labels": pod_labels},
                         "spec": pod_spec},
        },
    }


def service_for_runtime(cr: dict) -> dict:
    name = cr["metadata"]["name"]
    port = cr["spec"].get("tpuConfig", {}).get("port", 8000)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{name}-service", cr),
        "spec": {
            "selector": {"tpuruntime": name},
            "ports": [{"port": port, "targetPort": port, "name": "http"}],
        },
    }


def pvc_for_runtime(cr: dict) -> dict | None:
    storage = cr["spec"].get("storage", {})
    if not storage.get("pvcStorage"):
        return None
    name = cr["metadata"]["name"]
    spec: dict = {
        "accessModes": ["ReadWriteOnce"],
        "resources": {"requests": {"storage": storage["pvcStorage"]}},
    }
    if storage.get("storageClass"):
        spec["storageClassName"] = storage["storageClass"]
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": _meta(f"{name}-pvc", cr),
        "spec": spec,
    }


def router_args(spec: dict) -> list[str]:
    args = [
        "-m", "vllm_production_stack_tpu.router.app",
        "--port", str(spec.get("port", 8000)),
        "--service-discovery", spec.get("serviceDiscovery", "k8s_pod_ip"),
        "--routing-logic", spec.get("routingLogic", "roundrobin"),
    ]
    if spec.get("serviceDiscovery") == "static":
        args += ["--static-backends", spec.get("staticBackends", ""),
                 "--static-models", spec.get("staticModels", "")]
    elif spec.get("k8sLabelSelector"):
        args += ["--k8s-label-selector", spec["k8sLabelSelector"]]
    if spec.get("sessionKey"):
        args += ["--session-key", spec["sessionKey"]]
    if spec.get("kvControllerURL"):
        args += ["--kv-controller-url", spec["kvControllerURL"]]
    if spec.get("engineScrapeInterval"):
        args += ["--engine-stats-interval", str(spec["engineScrapeInterval"])]
    if spec.get("requestStatsWindow"):
        args += ["--request-stats-window", str(spec["requestStatsWindow"])]
    args += [str(a) for a in spec.get("extraArgs", [])]
    return args


def deployment_for_router(cr: dict) -> dict:
    spec = cr["spec"]
    name = cr["metadata"]["name"]
    image = spec.get("image", {})
    labels = {"app": f"{name}-router"}
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"{name}-router", cr, labels),
        "spec": {
            "replicas": spec.get("replicas", 1),
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [{
                    "name": "router",
                    "image": f"{image.get('repository', 'tpu-stack-router')}:"
                             f"{image.get('tag', 'latest')}",
                    "command": ["python"],
                    "args": router_args(spec),
                    "ports": [{
                        "containerPort": spec.get("port", 8000),
                        "name": "http",
                    }],
                    "livenessProbe": {
                        "httpGet": {"path": "/health", "port": "http"},
                        "periodSeconds": 10,
                    },
                }]},
            },
        },
    }


def deployment_for_cacheserver(cr: dict) -> dict:
    """The KV lookup controller half of the CacheServer CR (the component
    KV-aware routing queries; reference embeds the LMCache controller
    in-router, routing_logic.py:222-344 — here it is its own deployment)."""
    spec = cr["spec"]
    name = cr["metadata"]["name"]
    image = spec.get("image", {})
    labels = {"app": f"{name}-kv-controller"}
    args = ["-m", "vllm_production_stack_tpu.engine.kv_controller",
            "--port", str(spec.get("port", 9000))]
    if spec.get("engines"):
        args += ["--engines", ",".join(spec["engines"])]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"{name}-kv-controller", cr, labels),
        "spec": {
            "replicas": spec.get("replicas", 1),
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [{
                    "name": "kv-controller",
                    "image": f"{image.get('repository', 'tpu-stack-router')}:"
                             f"{image.get('tag', 'latest')}",
                    "command": ["python"],
                    "args": args,
                    "ports": [{
                        "containerPort": spec.get("port", 9000),
                        "name": "http",
                    }],
                }]},
            },
        },
    }


def deployment_for_kvstore(cr: dict) -> dict:
    """The KV STORAGE server half of the CacheServer CR — the process that
    holds KV bytes off-engine (the reference's lmcache_experimental_server
    deployment, helm deployment-cache-server.yaml:1-74). Engines point
    `--remote-kv-url tpukv://<name>-kv-store:<port>` at its Service."""
    spec = cr["spec"]
    name = cr["metadata"]["name"]
    image = spec.get("image", {})
    port = spec.get("storePort", 9200)
    labels = {"app": f"{name}-kv-store"}
    args = ["-m", "vllm_production_stack_tpu.kvstore.server",
            "--port", str(port),
            "--max-size-gib", str(spec.get("maxSizeGib", 4))]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"{name}-kv-store", cr, labels),
        "spec": {
            # the store is stateful-in-RAM; one replica per CR (scale by
            # sharding across CRs, not replicas — replicas would split the
            # hash space randomly and halve the hit rate)
            "replicas": 1 if spec.get("replicas", 1) > 0 else 0,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [{
                    "name": "kv-store",
                    "image": f"{image.get('repository', 'tpu-stack-router')}:"
                             f"{image.get('tag', 'latest')}",
                    "command": ["python"],
                    "args": args,
                    "ports": [{"containerPort": port, "name": "http"}],
                    "readinessProbe": {
                        "httpGet": {"path": "/health", "port": port},
                        "periodSeconds": 5,
                    },
                }]},
            },
        },
    }


def service_for_kvstore(cr: dict) -> dict:
    name = cr["metadata"]["name"]
    port = cr["spec"].get("storePort", 9200)
    labels = {"app": f"{name}-kv-store"}
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{name}-kv-store", cr, labels),
        "spec": {
            "selector": labels,
            "ports": [{"port": port, "targetPort": port, "name": "http"}],
        },
    }
