"""Reconcilers for the four CRDs.

Same control loops as the reference's Go controllers:
- TPURuntime  → Service → PVC → Deployment, drift detection, status from
  deployment replica counts (vllmruntime_controller.go:57-187, 624-735)
- TPURouter   → router Deployment + args from spec
  (vllmrouter_controller.go:62-195)
- CacheServer → KV-controller Deployment (cacheserver_controller.go:54-133)
- LoraAdapter → desired placement over ready pods labeled with the base
  model, diffed against live /v1/models registrations, loaded/unloaded via
  the engines' /v1/load_lora_adapter (loraadapter_controller.go:73-232,
  582-693 — control plane talking straight to data-plane HTTP)
"""

from __future__ import annotations

import aiohttp

from ..utils.logging import init_logger
from . import resources
from .k8s_client import K8sClient

logger = init_logger(__name__)


class PermanentDownloadError(Exception):
    """Adapter source spec the sidecar can never satisfy — surfaces as an
    Error phase on the CR instead of an eternal silent Loading loop."""


def _spec_drifted(live: dict, desired: dict) -> bool:
    """Compare the fields the operator owns (reference deploymentNeedsUpdate
    checks replicas/model/image/resources/env diff, :624-705). Pod-level
    placement fields (nodeSelector, volumes) and template labels are owned
    too — a tpuTopology or storage change must roll the deployment. Fields
    the apiserver defaults (strategy, probes' scheme, ...) are deliberately
    NOT compared, or every loop would look drifted on a real cluster."""
    lspec, dspec = live.get("spec", {}), desired["spec"]
    if lspec.get("replicas") != dspec.get("replicas"):
        return True
    lt, dt = lspec["template"], dspec["template"]
    if lt["metadata"].get("labels") != dt["metadata"].get("labels"):
        return True
    lp, dp = lt["spec"], dt["spec"]
    if lp.get("nodeSelector") != dp.get("nodeSelector"):
        return True
    if lp.get("volumes") != dp.get("volumes"):
        return True
    lc, dc = lp["containers"][0], dp["containers"][0]
    return any(
        lc.get(f) != dc.get(f)
        for f in ("image", "args", "env", "resources", "volumeMounts",
                  "ports")
    )


class TPURuntimeReconciler:
    plural = "tpuruntimes"

    def __init__(self, client: K8sClient):
        self.c = client

    async def reconcile(self, cr: dict) -> None:
        name = cr["metadata"]["name"]
        await self.c.apply(self.c.services, resources.service_for_runtime(cr))
        pvc = resources.pvc_for_runtime(cr)
        if pvc is not None and await self.c.get(
            self.c.pvcs(pvc["metadata"]["name"])
        ) is None:
            # PVCs are immutable-ish: create once, never replace
            await self.c.create(self.c.pvcs(), pvc)
        desired = resources.deployment_for_runtime(cr)
        live = await self.c.get(self.c.deployments(desired["metadata"]["name"]))
        if live is None or _spec_drifted(live, desired):
            await self.c.apply(self.c.deployments, desired)
            logger.info("TPURuntime %s: deployment %s",
                        name, "created" if live is None else "updated")
        # status from deployment replica counts
        live = await self.c.get(self.c.deployments(desired["metadata"]["name"]))
        st = (live or {}).get("status", {})
        ready = st.get("readyReplicas", 0) or 0
        want = cr["spec"].get("replicas", 1)
        await self.c.patch_status(self.c.crs(self.plural, name), {
            "replicas": want,
            "readyReplicas": ready,
            "phase": "Ready" if ready >= want else "Progressing",
        })


class TPURouterReconciler:
    plural = "tpurouters"

    def __init__(self, client: K8sClient):
        self.c = client

    async def reconcile(self, cr: dict) -> None:
        name = cr["metadata"]["name"]
        desired = resources.deployment_for_router(cr)
        live = await self.c.get(self.c.deployments(desired["metadata"]["name"]))
        if live is None or _spec_drifted(live, desired):
            await self.c.apply(self.c.deployments, desired)
        runtimes = await self.c.list(self.c.crs("tpuruntimes"))
        await self.c.patch_status(self.c.crs(self.plural, name), {
            "activeRuntimes": [r["metadata"]["name"] for r in runtimes],
            "phase": "Ready",
        })


class CacheServerReconciler:
    plural = "cacheservers"

    def __init__(self, client: K8sClient):
        self.c = client

    async def reconcile(self, cr: dict) -> None:
        # two halves: the KV STORAGE server (holds KV bytes off-engine — the
        # LMCache-server equivalent) and the KV lookup controller (answers
        # the KV-aware router's /kv/lookup)
        for desired in (
            resources.deployment_for_kvstore(cr),
            resources.deployment_for_cacheserver(cr),
        ):
            live = await self.c.get(
                self.c.deployments(desired["metadata"]["name"])
            )
            if live is None or _spec_drifted(live, desired):
                await self.c.apply(self.c.deployments, desired)
        svc = resources.service_for_kvstore(cr)
        live_svc = await self.c.get(self.c.services(svc["metadata"]["name"]))

        def _port_pairs(obj):
            # compare only the fields we set: the apiserver defaults others
            # (protocol, nodePort), which would otherwise read as permanent
            # drift and re-apply on every reconcile
            return [
                (p.get("port"), p.get("targetPort"))
                for p in (obj or {}).get("spec", {}).get("ports", [])
            ]

        if live_svc is None or _port_pairs(live_svc) != _port_pairs(svc):
            # re-apply on drift too (a storePort edit must retarget the
            # Service, not just the Deployment)
            await self.c.apply(self.c.services, svc)
        await self.c.patch_status(
            self.c.crs(self.plural, cr["metadata"]["name"]), {"phase": "Ready"}
        )


class LoraAdapterReconciler:
    plural = "loraadapters"
    # finalizer-based delete (reference loraadapter_controller.go:73-232):
    # a deleted CR must unload its adapter from every pod BEFORE the object
    # disappears, or adapters stay loaded forever
    FINALIZER = "production-stack.tpu.ai/lora-unload"

    def __init__(self, client: K8sClient, http: aiohttp.ClientSession,
                 engine_port: int = 8000, sidecar_port: int = 30090):
        self.c = client
        self.http = http
        self.engine_port = engine_port
        self.sidecar_port = sidecar_port

    async def _ready_pods(self, base_model: str) -> list[dict]:
        from .resources import label_safe

        pods = await self.c.list(
            self.c.pods(), label_selector=f"model={label_safe(base_model)}"
        )
        out = []
        for p in pods:
            conds = {
                c["type"]: c["status"]
                for c in p.get("status", {}).get("conditions", [])
            }
            if conds.get("Ready") == "True" and p["status"].get("podIP"):
                out.append(p)
        return out

    def _engine_url(self, pod: dict) -> str:
        """Data-plane URL of an engine pod (tests override to point at
        loopback TestServers)."""
        return f"http://{pod['status']['podIP']}:{self.engine_port}"

    def _sidecar_url(self, pod: dict) -> str:
        return f"http://{pod['status']['podIP']}:{self.sidecar_port}"

    async def _ensure_downloaded(self, pod: dict, spec: dict) -> str | None:
        """Non-local adapter sources land on the pod's PVC via its download
        sidecar first (reference: HF download through the sidecar's
        /model/download on port 30090, loraadapter_controller.go:334-391).
        Returns the pod-local path, or None on failure."""
        src = spec["adapterSource"]
        if src.get("type", "local") == "local":
            return src.get("adapterPath", "")
        body = {
            "source": "hf" if src["type"] == "huggingface" else src["type"],
            "model_id": src.get("adapterPath"),
            "url": src.get("adapterPath"),
            "target_dir": src.get("adapterName")
            or src.get("adapterPath", "").replace("/", "--"),
        }
        import asyncio

        try:
            async with self.http.post(
                self._sidecar_url(pod) + "/model/download", json=body,
                # downloads run long; the operator's shared 15s session
                # timeout would cancel every real fetch
                timeout=aiohttp.ClientTimeout(total=900),
            ) as resp:
                if resp.status == 400:  # permanent: bad source spec
                    detail = (await resp.json()).get("error", "")
                    raise PermanentDownloadError(detail)
                if resp.status != 200:
                    logger.warning(
                        "sidecar download on %s: HTTP %d",
                        pod["metadata"]["name"], resp.status,
                    )
                    return None
                return (await resp.json()).get("local_path")
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("sidecar download on %s failed: %s",
                           pod["metadata"]["name"], e)
            return None

    async def _registrations(self, url: str) -> set[str] | None:
        """Adapters live on one engine, from its /v1/models (the reference
        reconciles against exactly this output, :613-693). None = the pod's
        state is UNKNOWN (unreachable/garbled) — callers must not treat
        that as 'adapter absent' (the finalizer would leak the adapter)."""
        import asyncio
        import json

        try:
            async with self.http.get(url + "/v1/models") as resp:
                data = await resp.json()
            return {
                m["id"] for m in data.get("data", [])
                if m.get("parent") is not None
            }
        except (aiohttp.ClientError, asyncio.TimeoutError,
                json.JSONDecodeError, KeyError, TypeError) as e:
            logger.warning("reading /v1/models from %s failed: %s", url, e)
            return None

    def _placement_targets(
        self,
        pods: list[dict],
        regs_by_pod: dict[str, set[str]],
        adapter_name: str,
        placement: dict,
    ) -> set[str]:
        """Pod names that should carry the adapter, per placement.algorithm
        (crd-loraadapter.yaml): `ordered` (and `default`) packs the first N
        name-sorted pods — the reference's first-N behavior
        (loraadapter_controller.go:394-441); `equalized` picks the N pods
        carrying the fewest OTHER adapters (live registrations), name-sorted
        on ties, so a fleet's adapters spread instead of piling onto pod-0."""
        want_n = placement.get("replicas") or len(pods)
        algorithm = placement.get("algorithm") or "default"
        if algorithm == "equalized":
            def load_key(p):
                pod_name = p["metadata"]["name"]
                others = regs_by_pod.get(pod_name, set()) - {adapter_name}
                return (len(others), pod_name)

            chosen = sorted(pods, key=load_key)[:want_n]
        else:  # default / ordered: deterministic name order, first N
            chosen = sorted(
                pods, key=lambda p: p["metadata"]["name"]
            )[:want_n]
        return {p["metadata"]["name"] for p in chosen}

    async def reconcile(self, cr: dict) -> None:
        name = cr["metadata"]["name"]
        meta = cr["metadata"]
        if meta.get("deletionTimestamp"):
            await self._finalize(cr)
            return
        if self.FINALIZER not in meta.get("finalizers", []):
            meta.setdefault("finalizers", []).append(self.FINALIZER)
            updated = await self.c.replace(self.c.crs(self.plural, name), cr)
            if updated is None:
                # the CR vanished between our read and the finalizer PUT
                # (deleted before any finalizer pinned it): loading now
                # would leak an adapter no CR will ever unload
                return
            cr = updated
        spec = cr["spec"]
        adapter_name = spec["adapterSource"].get("adapterName") or name
        pods = await self._ready_pods(spec["baseModel"])
        placement = spec.get("placement", {})
        regs_by_pod = {}
        for pod in pods:
            regs = await self._registrations(self._engine_url(pod))
            # unknown state reads as empty here: a load attempt on an
            # unreachable pod just fails and retries next reconcile
            regs_by_pod[pod["metadata"]["name"]] = (
                regs if regs is not None else set()
            )
        target_names = self._placement_targets(
            pods, regs_by_pod, adapter_name, placement
        )

        loaded: list[dict] = []
        permanent_error: str | None = None
        for pod in pods:
            ip = pod["status"]["podIP"]
            is_target = pod["metadata"]["name"] in target_names
            url = self._engine_url(pod)
            regs = regs_by_pod[pod["metadata"]["name"]]
            if is_target and adapter_name not in regs:
                try:
                    path = await self._ensure_downloaded(pod, spec)
                except PermanentDownloadError as e:
                    permanent_error = str(e)
                    continue
                if path is None:
                    continue  # transient; retry next reconcile loop
                try:
                    async with self.http.post(
                        url + "/v1/load_lora_adapter",
                        json={"lora_name": adapter_name, "lora_path": path},
                    ) as resp:
                        if resp.status == 200:
                            regs.add(adapter_name)
                        else:
                            logger.warning(
                                "load %s on %s: HTTP %d", adapter_name, url,
                                resp.status,
                            )
                except aiohttp.ClientError as e:
                    logger.warning("load %s on %s failed: %s",
                                   adapter_name, url, e)
            elif not is_target and adapter_name in regs:
                try:
                    async with self.http.post(
                        url + "/v1/unload_lora_adapter",
                        json={"lora_name": adapter_name},
                    ) as resp:
                        if resp.status == 200:
                            regs.discard(adapter_name)
                except aiohttp.ClientError:
                    pass
            if adapter_name in regs:
                loaded.append({
                    "pod": pod["metadata"]["name"], "podIP": ip,
                })
        requested = placement.get("replicas") or len(pods)
        status: dict = {"loadedAdapters": loaded}
        if permanent_error:
            status["phase"] = "Error"
            status["reason"] = permanent_error
        elif not pods:
            status["phase"] = "Pending"  # no ready base-model pods
        elif loaded and len(loaded) >= requested:
            status["phase"] = "Loaded"
        else:
            status["phase"] = "Loading"
        await self.c.patch_status(self.c.crs(self.plural, name), status)

    async def _finalize(self, cr: dict) -> None:
        """Delete path: unload the adapter from every pod that carries it,
        then drop the finalizer so the apiserver completes the delete. An
        unreachable pod keeps the finalizer (retry next reconcile) — better
        a stuck delete than a leaked adapter."""
        from .resources import label_safe

        name = cr["metadata"]["name"]
        spec = cr["spec"]
        adapter_name = spec["adapterSource"].get("adapterName") or name
        all_unloaded = True
        # ALL pods carrying the base model, ready or not — a NotReady pod
        # may still hold the adapter and come back
        pods = await self.c.list(
            self.c.pods(),
            label_selector=f"model={label_safe(spec['baseModel'])}",
        )
        for pod in pods:
            if not pod.get("status", {}).get("podIP"):
                continue  # never scheduled/addressable: nothing loaded
            url = self._engine_url(pod)
            regs = await self._registrations(url)
            if regs is None:
                # state UNKNOWN: keep the finalizer and retry — better a
                # stuck delete than a leaked adapter
                all_unloaded = False
                continue
            if adapter_name not in regs:
                continue
            try:
                async with self.http.post(
                    url + "/v1/unload_lora_adapter",
                    json={"lora_name": adapter_name},
                ) as resp:
                    if resp.status != 200:
                        all_unloaded = False
            except aiohttp.ClientError as e:
                logger.warning(
                    "finalizer unload of %s on %s failed: %s",
                    adapter_name, url, e,
                )
                all_unloaded = False
        if not all_unloaded:
            return
        finalizers = [
            f for f in cr["metadata"].get("finalizers", [])
            if f != self.FINALIZER
        ]
        cr["metadata"]["finalizers"] = finalizers
        await self.c.replace(self.c.crs(self.plural, name), cr)
