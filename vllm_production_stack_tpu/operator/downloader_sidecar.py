"""Adapter/model download sidecar.

Reference: scripts/huggingface_downloader.py — a FastAPI service on port
30090 in the engine pod (docker/Dockerfile.sidecar) that the LoRA controller
calls via POST /model/download to land HF repos on the shared PVC
(loraadapter_controller.go:334-391). Same contract here on aiohttp:

    POST /model/download {"source": "hf|local|http",
                          "model_id": "...",        # hf: repo id
                          "url": "...",             # http: file URL
                          "path": "...",            # local: source dir
                          "target_dir": "relative/subdir"}
    → {"status": "ok", "local_path": "/data/models/<target_dir>"}

Downloads are idempotent (a completed marker short-circuits re-downloads)
and serialized per target dir. `hf` needs egress + huggingface_hub; `local`
copies from an already-mounted volume; `http` fetches a single file —
enough for adapters exported as a tarball-free safetensors pair.

Run: python -m vllm_production_stack_tpu.operator.downloader_sidecar \
        --port 30090 --base-dir /data/models
"""

from __future__ import annotations

import argparse
import asyncio
import os
import shutil

from aiohttp import web

from ..utils.logging import init_logger

logger = init_logger(__name__)

_MARKER = ".download-complete"


def _spec_key(spec: dict) -> str:
    """Identity of WHAT was downloaded — the idempotency marker stores it so
    a changed source (new repo/revision/url under the same target_dir)
    re-downloads instead of silently serving stale weights."""
    import hashlib

    fields = (spec.get("source", "hf"), spec.get("model_id"),
              spec.get("url"), spec.get("path"))
    return hashlib.sha256(repr(fields).encode()).hexdigest()[:32]


class DownloaderSidecar:
    def __init__(self, base_dir: str):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self._locks: dict[str, asyncio.Lock] = {}

    def _target(self, target_dir: str) -> str:
        """Resolve + confine the target under base_dir (no path escapes)."""
        path = os.path.abspath(os.path.join(self.base_dir, target_dir))
        if not path.startswith(self.base_dir + os.sep):
            raise ValueError(f"target_dir {target_dir!r} escapes the base dir")
        return path

    async def download(self, spec: dict) -> str:
        target = self._target(spec.get("target_dir") or spec.get("model_id", ""))
        lock = self._locks.setdefault(target, asyncio.Lock())
        key = _spec_key(spec)
        async with lock:
            marker = os.path.join(target, _MARKER)
            loop = asyncio.get_running_loop()
            # marker check + stale-target rmtree are file I/O (rmtree of a
            # multi-GB model dir takes seconds): off the event loop — the
            # per-target asyncio lock stays held across the await, which
            # is its job (serialize work on one target dir), but health
            # probes and downloads for OTHER targets keep flowing
            fresh = await loop.run_in_executor(
                None, self._prepare_target, target, marker, key
            )
            if not fresh:
                return target  # idempotent: same source already landed
            source = spec.get("source", "hf")
            if source == "local":
                await loop.run_in_executor(
                    None, self._copy_local, spec["path"], target
                )
            elif source == "http":
                await self._fetch_http(spec["url"], target)
            elif source == "hf":
                await loop.run_in_executor(
                    None, self._snapshot_hf, spec["model_id"], target
                )
            elif source == "s3":
                await loop.run_in_executor(
                    None, self._fetch_s3, spec["url"] or spec["model_id"],
                    target,
                )
            else:
                raise ValueError(f"unknown source {source!r}")
            await loop.run_in_executor(None, self._write_marker, marker, key)
            logger.info("downloaded %s -> %s", spec, target)
            return target

    @staticmethod
    def _prepare_target(target: str, marker: str, key: str) -> bool:
        """Executor-side: True iff the target needs (re-)downloading.
        A marker for a DIFFERENT source wipes the target first."""
        if os.path.exists(marker):
            with open(marker) as f:
                if f.read() == key:
                    return False
            shutil.rmtree(target)
        os.makedirs(target, exist_ok=True)
        return True

    @staticmethod
    def _write_marker(marker: str, key: str) -> None:
        with open(marker, "w") as f:
            f.write(key)

    @staticmethod
    def _copy_local(src: str, target: str) -> None:
        for name in os.listdir(src):
            s = os.path.join(src, name)
            d = os.path.join(target, name)
            if os.path.isdir(s):
                shutil.copytree(s, d, dirs_exist_ok=True)
            else:
                shutil.copy2(s, d)

    async def _fetch_http(self, url: str, target: str) -> None:
        import aiohttp
        from urllib.parse import urlparse

        # basename of the URL PATH — query strings (presigned URLs) must not
        # leak into the on-disk filename
        name = os.path.basename(urlparse(url).path) or "download"
        loop = asyncio.get_running_loop()
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=600)
        ) as sess:
            async with sess.get(url) as resp:
                resp.raise_for_status()
                # open + per-chunk writes are disk I/O: keep them off the
                # event loop so a slow volume can't stall health probes
                f = await loop.run_in_executor(
                    None, open, os.path.join(target, name), "wb"
                )
                try:
                    async for chunk in resp.content.iter_chunked(1 << 20):
                        await loop.run_in_executor(None, f.write, chunk)
                finally:
                    await loop.run_in_executor(None, f.close)

    @staticmethod
    def _fetch_s3(uri: str, target: str) -> None:
        """s3://bucket/prefix → target (needs boto3 in the sidecar image;
        credentials via the pod's AWS_* env, the reference's
        credentialsSecret contract)."""
        try:
            import boto3
        except ImportError as e:
            raise ValueError(
                "s3 adapter sources need boto3 in the sidecar image"
            ) from e
        from urllib.parse import urlparse

        parsed = urlparse(uri)
        bucket, prefix = parsed.netloc, parsed.path.lstrip("/")
        s3 = boto3.client("s3")
        paginator = s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                rel = obj["Key"][len(prefix):].lstrip("/") or                     os.path.basename(obj["Key"])
                dest = os.path.join(target, rel)
                os.makedirs(os.path.dirname(dest) or target, exist_ok=True)
                s3.download_file(bucket, obj["Key"], dest)

    @staticmethod
    def _snapshot_hf(model_id: str, target: str) -> None:
        from huggingface_hub import snapshot_download

        snapshot_download(
            repo_id=model_id, local_dir=target,
            token=os.environ.get("HF_TOKEN"),
        )

    # -- HTTP surface ------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/model/download", self._handle_download)
        app.router.add_get("/health", self._handle_health)
        return app

    async def _handle_download(self, request: web.Request) -> web.Response:
        spec = await request.json()
        if not (spec.get("model_id") or spec.get("path") or spec.get("url")):
            return web.json_response(
                {"error": "model_id, path, or url is required"}, status=400
            )
        try:
            path = await self.download(spec)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:
            logger.exception("download failed")
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"status": "ok", "local_path": path})

    async def _handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "base_dir": self.base_dir})


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="model download sidecar")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=30090)
    p.add_argument("--base-dir", default="/data/models")
    args = p.parse_args(argv)
    web.run_app(
        DownloaderSidecar(args.base_dir).build_app(),
        host=args.host, port=args.port, access_log=None,
    )


if __name__ == "__main__":
    main()
