"""Kubernetes operator for the TPU serving stack (reference: operator/ —
Go/kubebuilder with 4 CRDs; here a Python control plane over raw K8s REST,
same reconcile semantics)."""
