"""KV storage server: content-hash → KV-block bytes with a byte-budget LRU.

The remote tier of the KV hierarchy (HBM → host ring → THIS). Engines push
blocks that fall off their host ring (write-behind) and fetch runs of blocks
their local tiers miss, so a prompt prefilled on engine A warms engine B's
prefill — the cross-engine KV sharing the reference gets from the LMCache
server (deployment-cache-server.yaml:1-74, `lm://` wiring in
_helpers.tpl:195-197).

Wire protocol (plain HTTP, framing documented per handler):
  GET  /health               liveness + occupancy
  GET  /metrics              Prometheus text (tpukv_* series)
  PUT  /v1/blocks/{hash}     raw block bytes; X-KV-Shape/X-KV-Dtype/
                             X-KV-Fingerprint headers
  GET  /v1/blocks/{hash}     raw block bytes back (404 when absent)
  POST /v1/contains          {"fingerprint", "hashes": [str]} ->
                             {"present": [bool]}
  POST /v1/mget              {"fingerprint", "hashes": [str]} -> binary
                             frames of the CONSECUTIVE present prefix

Blocks are namespaced by the engine's model fingerprint (weights identity +
KV dtype): two models' identical token streams must never share KV bytes.
"""

from __future__ import annotations

import argparse
import asyncio
from collections import OrderedDict
from dataclasses import dataclass

from aiohttp import web

from ..utils.logging import init_logger

logger = init_logger(__name__)


@dataclass
class StoreStats:
    puts: int = 0
    hits: int = 0
    misses: int = 0
    evictions: int = 0


class BlockStore:
    """Byte-budget LRU of KV blocks keyed by (fingerprint, hash)."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = capacity_bytes
        self._data: OrderedDict[tuple[str, str], tuple[bytes, dict]] = (
            OrderedDict()
        )
        self.total_bytes = 0
        self.stats = StoreStats()

    def __len__(self) -> int:
        return len(self._data)

    def put(self, fp: str, h: str, payload: bytes, meta: dict) -> None:
        key = (fp, h)
        old = self._data.pop(key, None)
        if old is not None:
            self.total_bytes -= len(old[0])
        self._data[key] = (payload, meta)
        self.total_bytes += len(payload)
        self.stats.puts += 1
        while self.total_bytes > self.capacity_bytes and len(self._data) > 1:
            (_, _), (evicted, _m) = self._data.popitem(last=False)
            self.total_bytes -= len(evicted)
            self.stats.evictions += 1

    def get(self, fp: str, h: str) -> tuple[bytes, dict] | None:
        entry = self._data.get((fp, h))
        if entry is None:
            self.stats.misses += 1
            return None
        self._data.move_to_end((fp, h))
        self.stats.hits += 1
        return entry

    def contains(self, fp: str, h: str) -> bool:
        return (fp, h) in self._data


def _meta_frame(h: str, payload: bytes, meta: dict) -> bytes:
    """One mget frame in the shared streaming wire format
    (engine/kv_transfer.py: raw_frame / FrameParser) — the PD transport and
    the remote store speak the same framing. At-rest-encoded payloads
    (engine/kv_codec) pass their codec metadata straight through: the
    store never decodes, the fetching engine dequantizes on adopt."""
    from ..engine.kv_transfer import raw_frame

    shape = [int(d) for d in meta["shape"].split(",") if d]
    return raw_frame(
        h, payload, meta["dtype"], shape,
        codec=meta.get("codec", ""),
        group=int(meta.get("group") or 0),
        scale_nbytes=int(meta.get("scale_nbytes") or 0),
    )


class KVStoreServer:
    def __init__(self, capacity_bytes: int):
        self.store = BlockStore(capacity_bytes)

    async def h_put(self, request: web.Request) -> web.Response:
        h = request.match_info["hash"]
        fp = request.headers.get("X-KV-Fingerprint", "")
        meta = {
            "shape": request.headers.get("X-KV-Shape", ""),
            "dtype": request.headers.get("X-KV-Dtype", ""),
            # at-rest codec metadata (engine/kv_codec): stored opaquely,
            # echoed on GET headers and mget frames
            "codec": request.headers.get("X-KV-Codec", ""),
            "group": request.headers.get("X-KV-Group", "0"),
            "scale_nbytes": request.headers.get("X-KV-Scale-Bytes", "0"),
        }
        payload = await request.read()
        if not payload:
            return web.json_response(
                {"error": "empty block payload"}, status=400
            )
        self.store.put(fp, h, payload, meta)
        # piggyback the store's fill fraction on the ack: engines surface
        # it as tpu:engine_kv_tier_usage_perc{tier="remote"} without a
        # dedicated polling round trip (docs/29-saturation-slo.md)
        usage = (
            self.store.total_bytes / self.store.capacity_bytes
            if self.store.capacity_bytes > 0 else 0.0
        )
        return web.json_response(
            {"stored": True, "nbytes": len(payload),
             "usage_perc": round(usage, 6)},
            headers={"X-Store-Usage": f"{usage:.6f}"},
        )

    async def h_get(self, request: web.Request) -> web.Response:
        h = request.match_info["hash"]
        fp = request.query.get("fingerprint", "")
        entry = self.store.get(fp, h)
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        payload, meta = entry
        headers = {
            "X-KV-Shape": meta["shape"],
            "X-KV-Dtype": meta["dtype"],
        }
        if meta.get("codec"):
            headers["X-KV-Codec"] = meta["codec"]
            headers["X-KV-Group"] = str(meta.get("group", "0"))
            headers["X-KV-Scale-Bytes"] = str(meta.get("scale_nbytes", "0"))
        return web.Response(
            body=payload,
            headers=headers,
            content_type="application/octet-stream",
        )

    async def h_contains(self, request: web.Request) -> web.Response:
        body = await request.json()
        fp = body.get("fingerprint", "")
        present = [
            self.store.contains(fp, str(h)) for h in body.get("hashes", [])
        ]
        return web.json_response({"present": present})

    async def h_mget(self, request: web.Request) -> web.Response:
        """Binary frames for the CONSECUTIVE present prefix of the requested
        hashes — prefix KV is only reusable as an unbroken chain, so the
        server stops at the first gap instead of shipping unusable blocks."""
        body = await request.json()
        fp = body.get("fingerprint", "")
        frames: list[bytes] = []
        for h in body.get("hashes", []):
            entry = self.store.get(fp, str(h))
            if entry is None:
                break
            payload, meta = entry
            frames.append(_meta_frame(str(h), payload, meta))
        return web.Response(
            body=b"".join(frames),
            headers={"X-KV-Count": str(len(frames))},
            content_type="application/octet-stream",
        )

    async def h_health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "ok",
                "blocks": len(self.store),
                "bytes": self.store.total_bytes,
                "capacity_bytes": self.store.capacity_bytes,
            }
        )

    async def h_metrics(self, request: web.Request) -> web.Response:
        s = self.store.stats
        lines = [
            "# TYPE tpukv_blocks gauge",
            f"tpukv_blocks {len(self.store)}",
            "# TYPE tpukv_bytes gauge",
            f"tpukv_bytes {self.store.total_bytes}",
            "# TYPE tpukv_capacity_bytes gauge",
            f"tpukv_capacity_bytes {self.store.capacity_bytes}",
            "# TYPE tpukv_puts_total counter",
            f"tpukv_puts_total {s.puts}",
            "# TYPE tpukv_hits_total counter",
            f"tpukv_hits_total {s.hits}",
            "# TYPE tpukv_misses_total counter",
            f"tpukv_misses_total {s.misses}",
            "# TYPE tpukv_evictions_total counter",
            f"tpukv_evictions_total {s.evictions}",
        ]
        return web.Response(text="\n".join(lines) + "\n")

    def build_app(self) -> web.Application:
        # blocks are a few MiB each; cap single uploads well above that
        app = web.Application(client_max_size=256 * 2**20)
        app.router.add_put("/v1/blocks/{hash}", self.h_put)
        app.router.add_get("/v1/blocks/{hash}", self.h_get)
        app.router.add_post("/v1/contains", self.h_contains)
        app.router.add_post("/v1/mget", self.h_mget)
        app.router.add_get("/health", self.h_health)
        app.router.add_get("/metrics", self.h_metrics)
        return app


def run_in_thread(capacity_bytes: int = 1 << 30, port: int = 0):
    """Start a KV store server on its own thread + event loop (tests and
    the engine-embedded mode). Returns (base_url, stop_fn, server)."""
    import socket
    import threading

    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

    server = KVStoreServer(capacity_bytes)
    loop = asyncio.new_event_loop()
    started = threading.Event()
    runner_box: dict = {}

    def worker():
        asyncio.set_event_loop(loop)

        async def start():
            runner = web.AppRunner(server.build_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()
            runner_box["runner"] = runner

        loop.run_until_complete(start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=worker, daemon=True, name="kvstore")
    t.start()
    started.wait(timeout=10)

    def stop():
        async def cleanup():
            await runner_box["runner"].cleanup()

        fut = asyncio.run_coroutine_threadsafe(cleanup(), loop)
        fut.result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)

    return f"http://127.0.0.1:{port}", stop, server


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU stack KV storage server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9200)
    p.add_argument(
        "--max-size-gib",
        type=float,
        default=4.0,
        help="byte budget for stored KV blocks (LRU beyond this)",
    )
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    server = KVStoreServer(int(args.max_size_gib * 2**30))
    logger.info(
        "KV store listening on %s:%d (budget %.1f GiB)",
        args.host, args.port, args.max_size_gib,
    )
    web.run_app(
        server.build_app(), host=args.host, port=args.port, print=None
    )


if __name__ == "__main__":
    main()
