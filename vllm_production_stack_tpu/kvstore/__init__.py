"""Remote KV cache store — the LMCache-server equivalent.

A standalone process that stores full KV blocks by content hash so engines
can share computed KV across pods (reference: `lmcache_experimental_server`
deployed by helm/templates/deployment-cache-server.yaml:1-74 and wired into
engines as `LMCACHE_REMOTE_URL lm://host:port`,
vllmruntime_controller.go:337-374). Server: `kvstore.server`; engine-side
client/tier: `kvstore.client`.
"""
