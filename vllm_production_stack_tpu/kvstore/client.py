"""Engine-side remote KV tier: sync fetch path + write-behind store thread.

Sits behind the host ring (`engine/kv_host_tier.py`): blocks resolved or
evicted by the ring are pushed here asynchronously (a daemon writer thread —
the scheduler loop never blocks on a store), and prefix matches that run off
the end of the local tiers issue ONE batched `mget` for the remaining chain
(reference: LMCache remote backend behind `LMCACHE_REMOTE_URL`,
vllmruntime_controller.go:349-374).

Fetches are synchronous HTTP on the engine thread — a deliberate trade: one
round trip (<~ms in-cluster) buys back an entire prefill chunk's compute. A
failure trips a cooldown so a dead server costs one timeout per
`cooldown_s`, not one per prompt.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from urllib.parse import urlsplit

import numpy as np

from ..engine.kv_codec import EncodedKVBlock
from ..engine.kv_flow import NULL_FLOW
from ..utils.logging import init_logger

logger = init_logger(__name__)


def parse_store_url(url: str) -> tuple[str, int]:
    """Accepts `tpukv://host:port` (the stack's lm://-style scheme) or
    `http://host:port`."""
    parts = urlsplit(url if "//" in url else f"//{url}")
    if not parts.hostname:
        raise ValueError(f"invalid KV store URL {url!r}")
    return parts.hostname, parts.port or 9200


class _Conn:
    """One keep-alive HTTP connection; reconnects once on a stale socket."""

    def __init__(self, host: str, port: int, timeout: float):
        self.host, self.port, self.timeout = host, port, timeout
        self._c: http.client.HTTPConnection | None = None

    def request(
        self, method: str, path: str, body: bytes | None = None,
        headers: dict | None = None,
    ) -> tuple[int, dict, bytes]:
        for attempt in (0, 1):
            if self._c is None:
                self._c = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout
                )
            try:
                self._c.request(method, path, body=body, headers=headers or {})
                resp = self._c.getresponse()
                payload = resp.read()
                return resp.status, dict(resp.getheaders()), payload
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt:
                    raise
        raise OSError("unreachable")  # pragma: no cover

    def close(self) -> None:
        if self._c is not None:
            try:
                self._c.close()
            finally:
                self._c = None


@dataclass
class RemoteTierStats:
    stores: int = 0  # blocks pushed (writer thread, after dedupe)
    dropped: int = 0  # enqueued pushes abandoned (server error / cooldown)
    overflow: int = 0  # pushes rejected at the queue (never enqueued)
    fetches: int = 0  # mget round trips
    fetched_blocks: int = 0  # blocks served remote -> engine
    probe_hits: int = 0  # contains_run block hits (lookup probes)
    errors: int = 0


class RemoteKVTier:
    """Client half of the remote tier. All hashes travel as decimal strings
    (they're 128-bit; string form sidesteps any JSON integer-width trap)."""

    def __init__(
        self,
        url: str,
        fingerprint: str,
        timeout: float = 2.0,
        max_pending: int = 512,
        dedupe_capacity: int = 65536,
        cooldown_s: float = 5.0,
        flow=None,
        heartbeat=None,
        codec=None,
    ):
        self.host, self.port = parse_store_url(url)
        self.fingerprint = fingerprint
        self.cooldown_s = cooldown_s
        # at-rest codec (engine/kv_codec.KVAtRestCodec): PUT bodies ship
        # wire-encoded with codec headers; the server stores payloads
        # byte-agnostically and mget frames carry the codec metadata
        # back. The fingerprint namespace includes the codec spec, so a
        # store shared by a mixed-precision fleet never cross-serves.
        self.codec = codec
        self.stats = RemoteTierStats()
        # KV flow meter (engine/kv_flow.py): PUTs and fetches record
        # bytes/blocks/latency under tier="remote" — including failed
        # round trips at 0 bytes, so an outage reads as collapsing fetch
        # bandwidth instead of silence
        self.flow = flow if flow is not None else NULL_FLOW
        # last store-reported fill fraction (X-Store-Usage on PUT acks) —
        # the engine's tpu:engine_kv_tier_usage_perc{tier="remote"} source;
        # 0.0 until the first ack lands (docs/29-saturation-slo.md)
        self.last_usage_perc = 0.0
        self._fetch_conn = _Conn(self.host, self.port, timeout)
        self._store_conn = _Conn(self.host, self.port, timeout)
        # the fetch connection is shared by the engine step thread
        # (match_prefix / probe continuations) and the hydration fetcher
        # thread (chunked async loads, docs/31-hydration-planner.md) —
        # serialize round trips so interleaved requests can't corrupt the
        # keep-alive stream
        self._fetch_mu = threading.Lock()
        self._down_until = 0.0
        # hashes known stored (by US — other engines' pushes are invisible,
        # which only costs a redundant put); shared engine/writer thread
        self._stored: OrderedDict[int, None] = OrderedDict()
        self._inflight: set[int] = set()  # enqueued, not yet written
        self._stored_lock = threading.Lock()
        self._dedupe_capacity = dedupe_capacity
        self._q: queue.Queue = queue.Queue(maxsize=max_pending)
        self._enqueued = 0  # accepted into the queue (drain() accounting)
        # thread-liveness heartbeat (docs/37-flight-recorder.md,
        # flightrec.ThreadRegistry "kv_writer"): beaten per PUT, idle
        # while blocked on the empty queue — a writer wedged mid-PUT
        # (half-open store connection) is named instead of silently
        # parking the offload path
        self.heartbeat = heartbeat
        self._writer = threading.Thread(
            target=self._writer_loop, daemon=True, name="kv-remote-writer"
        )
        self._writer.start()

    # -- availability ------------------------------------------------------

    def _available(self) -> bool:
        return time.monotonic() >= self._down_until

    def _trip(self, err: Exception) -> None:
        self.stats.errors += 1
        self._down_until = time.monotonic() + self.cooldown_s
        logger.warning(
            "KV store %s:%d unreachable (%s); cooling down %.0fs",
            self.host, self.port, err, self.cooldown_s,
        )

    # -- store path (writer thread) ----------------------------------------

    def put_async(self, h: int, arr: np.ndarray) -> None:
        """Enqueue one block for the writer thread. Never blocks: a full
        queue drops the block (it is a CACHE — losing a push only costs a
        possible future recompute)."""
        with self._stored_lock:
            if h in self._stored:
                self._stored.move_to_end(h)
                return
            if h in self._inflight:  # resolve-push + evict-push race
                return
            self._inflight.add(h)
        try:
            self._q.put_nowait((h, arr))
            self._enqueued += 1
        except queue.Full:
            with self._stored_lock:
                self._inflight.discard(h)
            # NOT counted in `dropped`: drain() balances stores+dropped
            # against _enqueued, and this item never entered the queue
            self.stats.overflow += 1

    def _writer_loop(self) -> None:
        hb = self.heartbeat
        while True:
            if hb is not None:
                hb.idle()  # parked on an empty queue is not a stall
            item = self._q.get()
            if hb is not None:
                hb.beat()
            if item is None:
                if hb is not None:
                    hb.idle()
                return
            h, arr = item
            if not self._available():
                with self._stored_lock:
                    self._inflight.discard(h)
                self.stats.dropped += 1
                continue
            # encode to at-rest form unless the ring already did
            obj = arr
            if (
                self.codec is not None
                and self.codec.enabled
                and not isinstance(arr, EncodedKVBlock)
            ):
                obj = self.codec.encode(arr)
            headers = {
                "X-KV-Fingerprint": self.fingerprint,
                "Content-Type": "application/octet-stream",
            }
            if isinstance(obj, EncodedKVBlock):
                body = obj.payload
                logical = obj.logical_nbytes
                headers["X-KV-Shape"] = ",".join(
                    str(d) for d in obj.shape
                )
                headers["X-KV-Dtype"] = obj.dtype
                headers["X-KV-Codec"] = obj.codec
                headers["X-KV-Group"] = str(obj.group)
                headers["X-KV-Scale-Bytes"] = str(obj.scale_nbytes)
            else:
                body = np.ascontiguousarray(obj).tobytes()
                logical = len(body)
                headers["X-KV-Shape"] = ",".join(
                    str(d) for d in obj.shape
                )
                headers["X-KV-Dtype"] = obj.dtype.name
            t0 = time.perf_counter()
            try:
                status, resp_headers, _ = self._store_conn.request(
                    "PUT",
                    f"/v1/blocks/{h}",
                    body=body,
                    headers=headers,
                )
            except OSError as e:
                self.flow.record(
                    "remote", "out", 0, 0, time.perf_counter() - t0
                )
                self._trip(e)
                with self._stored_lock:
                    self._inflight.discard(h)
                self.stats.dropped += 1
                continue
            self.flow.record(
                "remote", "out",
                len(body) if status == 200 else 0,
                1 if status == 200 else 0,
                time.perf_counter() - t0,
                logical_nbytes=logical if status == 200 else 0,
            )
            if status == 200:
                self.stats.stores += 1
                usage = resp_headers.get("X-Store-Usage")
                if usage is not None:
                    try:
                        self.last_usage_perc = min(1.0, float(usage))
                    except ValueError:
                        pass
                with self._stored_lock:
                    self._inflight.discard(h)
                    self._stored[h] = None
                    while len(self._stored) > self._dedupe_capacity:
                        self._stored.popitem(last=False)
            else:
                with self._stored_lock:
                    self._inflight.discard(h)
                self.stats.dropped += 1

    # -- fetch path (engine thread) ----------------------------------------

    def contains_run(self, hashes: list[int]) -> int:
        """How many of `hashes` (in order, consecutively) the store holds —
        the /kv/lookup probe continuation. One round trip."""
        if not hashes or not self._available():
            return 0
        try:
            with self._fetch_mu:
                status, _, payload = self._fetch_conn.request(
                    "POST",
                    "/v1/contains",
                    body=json.dumps({
                        "fingerprint": self.fingerprint,
                        "hashes": [str(h) for h in hashes],
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                )
        except OSError as e:
            self._trip(e)
            return 0
        if status != 200:
            return 0
        present = json.loads(payload).get("present", [])
        n = 0
        for ok in present:
            if not ok:
                break
            n += 1
        self.stats.probe_hits += n
        return n

    def new_fetch_conn(self) -> _Conn:
        """A dedicated keep-alive connection for a long-lived fetch
        consumer (the hydration planner's fetcher thread) — its
        multi-second chunk mgets must never hold the shared fetch lock
        the step thread's probes and sync matches contend on."""
        return _Conn(self.host, self.port, self._fetch_conn.timeout)

    def fetch_run(
        self, hashes: list[int], conn: _Conn | None = None
    ) -> list:
        """The consecutive present prefix of `hashes`, one batched mget
        round trip — plain frames as arrays, at-rest frames as
        EncodedKVBlock (the pool dequantizes on adopt). `conn` routes the
        round trip over a dedicated connection (new_fetch_conn) instead
        of the shared, locked one.

        Partial failures degrade to partial SUCCESS: when the response
        stream goes corrupt mid-run (foreign-version store, truncated
        proxy body) the frames parsed before the fault are real blocks —
        they're returned, counted in `fetched_blocks`, and their bytes +
        the round trip's wall time land in the flow meter BEFORE the
        error path runs. The old all-or-nothing parse turned a one-frame
        corruption into a full-run cache miss and lost the timing of
        blocks that had already moved."""
        if not hashes or not self._available():
            return []
        from ..engine.kv_transfer import FrameParser

        t0 = time.perf_counter()
        out: list = []

        def _flow(nbytes: int, logical: int | None = None) -> None:
            self.flow.record(
                "remote", "in", nbytes, len(out),
                time.perf_counter() - t0, logical_nbytes=logical,
            )

        body = json.dumps({
            "fingerprint": self.fingerprint,
            "hashes": [str(h) for h in hashes],
        }).encode()
        try:
            if conn is not None:
                status, headers, payload = conn.request(
                    "POST", "/v1/mget", body=body,
                    headers={"Content-Type": "application/json"},
                )
            else:
                with self._fetch_mu:
                    status, headers, payload = self._fetch_conn.request(
                        "POST", "/v1/mget", body=body,
                        headers={"Content-Type": "application/json"},
                    )
        except OSError as e:
            _flow(0)  # a dead store IS ~0 fetch bandwidth — record it
            self._trip(e)
            return []
        if status != 200:
            _flow(0)
            return []
        self.stats.fetches += 1
        # decode_codec=False: at-rest frames come back as EncodedKVBlock
        # and dequantize at the pool's adopt boundary (_match_remote /
        # adopt_planned_run) — the fetch path holds wire-size RAM only
        parser = FrameParser(decode_codec=False)
        for h, arr in parser.feed_partial(payload):
            if len(out) >= len(hashes) or h != hashes[len(out)]:
                break  # non-consecutive frame; stop clean
            # copy: a frombuffer view would pin the ENTIRE multi-block
            # response buffer for as long as any one block stays referenced
            # (the host ring retains these). EncodedKVBlock payloads are
            # immutable bytes already detached from the response buffer.
            out.append(arr.copy() if isinstance(arr, np.ndarray) else arr)
            # it exists remotely — teach the dedupe set so eviction of the
            # promoted copy doesn't push it straight back
            with self._stored_lock:
                self._stored[h] = None
                while len(self._stored) > self._dedupe_capacity:
                    self._stored.popitem(last=False)
        self.stats.fetched_blocks += len(out)
        # wire vs logical from the parser's per-frame meta (frames past
        # the consecutive prefix were parsed but not adopted — exclude)
        meta = parser.frame_meta[: len(out)]
        _flow(sum(w for w, _ in meta), sum(lg for _, lg in meta))
        if parser.error is not None:
            # a malformed/foreign-version response must degrade to a cache
            # miss (here: the valid prefix) like every other remote-tier
            # failure — never fail the user's request from inside
            # match_prefix
            logger.warning(
                "malformed mget response after %d valid frames: %s",
                len(out), parser.error,
            )
            self.stats.errors += 1
        return out

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every enqueued store has been attempted (tests /
        graceful shutdown). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.stats.stores + self.stats.dropped >= self._enqueued:
                return True
            time.sleep(0.01)
        return False

    def close(self) -> None:
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._writer.join(timeout=5)
        self._fetch_conn.close()
        self._store_conn.close()
