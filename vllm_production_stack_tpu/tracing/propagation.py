"""W3C Trace Context propagation (traceparent header, version 00).

The router stamps `traceparent: 00-<trace_id>-<span_id>-<flags>` on every
upstream request so the engine's spans join the router's trace; a caller
already carrying a traceparent keeps its trace id (the router becomes a
child of the caller's span, standard distributed-tracing behavior). No
tracestate support: we propagate identity, not vendor baggage.
"""

from __future__ import annotations

import os

TRACEPARENT_HEADER = "traceparent"

_HEX = set("0123456789abcdef")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """(trace_id, parent_span_id) from a W3C traceparent, or None for a
    missing/malformed header. Malformed input is DROPPED, never raised:
    a bad client header must start a fresh trace, not 500 the request.
    All-zero ids are invalid per the spec (they mean "no trace")."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if version == "ff" or len(version) != 2 or not _is_hex(version):
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"
