"""OTLP export bridge — makes `init_otel`'s promise true, softly.

When the OpenTelemetry SDK is installed AND `router.tracing.init_otel`
(or any other code) installed a real `TracerProvider`, finished request
timelines are re-emitted through the provider's span processors as
`ReadableSpan`s carrying OUR ids — so the spans a Jaeger/Tempo backend
shows join into the same router→engine trace `/debug/requests` shows,
including a caller-supplied trace id. Without the SDK (the default
image) `resolve_otel_sink` returns None and the spine stays fully
in-process, zero deps.
"""

from __future__ import annotations

import os

from ..utils.logging import init_logger

logger = init_logger(__name__)


def resolve_otel_sink(service: str):
    """A callable(RequestTrace) exporting over the configured OTLP
    pipeline, or None when the SDK/provider/endpoint is absent."""
    if not os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT"):
        return None
    try:
        from opentelemetry import trace as ot_trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import (
            Event,
            ReadableSpan,
            TracerProvider,
        )
        from opentelemetry.trace import (
            SpanContext,
            SpanKind,
            Status,
            StatusCode,
            TraceFlags,
        )
    except ImportError:
        return None
    provider = ot_trace.get_tracer_provider()
    if not isinstance(provider, TracerProvider):
        # init_otel never ran (or failed): nothing to export through
        return None
    processor = getattr(provider, "_active_span_processor", None)
    if processor is None:
        return None
    resource = Resource.create({"service.name": service})

    def _ctx(trace_id: str, span_id: str, remote: bool = False) -> SpanContext:
        return SpanContext(
            trace_id=int(trace_id, 16),
            span_id=int(span_id, 16),
            is_remote=remote,
            trace_flags=TraceFlags(TraceFlags.SAMPLED),
        )

    def _readable(span, service_attrs=None) -> ReadableSpan:
        status = (
            Status(StatusCode.OK)
            if span.status == "ok"
            else Status(StatusCode.ERROR, span.status)
        )
        return ReadableSpan(
            name=span.name,
            context=_ctx(span.trace_id, span.span_id),
            parent=(
                _ctx(span.trace_id, span.parent_id, remote=True)
                if span.parent_id
                else None
            ),
            resource=resource,
            attributes={
                k: v
                for k, v in span.attrs.items()
                if isinstance(v, (str, bool, int, float))
            },
            events=[
                Event(
                    name=n,
                    attributes={
                        k: v
                        for k, v in a.items()
                        if isinstance(v, (str, bool, int, float))
                    },
                    timestamp=int(t * 1e9),
                )
                for t, n, a in span.events
            ],
            kind=SpanKind.SERVER,
            status=status,
            start_time=int(span.start * 1e9),
            end_time=int((span.end if span.end is not None else span.start) * 1e9),
        )

    def sink(trace) -> None:
        for span in (*trace.spans, trace.root):
            processor.on_end(_readable(span))

    logger.info("request-trace OTLP export active (service %s)", service)
    return sink
