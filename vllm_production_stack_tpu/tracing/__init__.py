"""Dependency-free request-tracing spine (docs/28-request-tracing.md).

Every request crossing the stack gets a structured span/event timeline,
correlated end to end by one trace id: the router opens an ingress span
(routing decision, failover attempts, QoS verdict, upstream TTFB) and
propagates W3C `traceparent` to the engine, whose spans (admission, queue
wait, prefill, per-decode-window events) join the same trace. Timelines
live in an in-process ring buffer served by `/debug/requests`; when the
OpenTelemetry SDK is installed AND `init_otel` configured a provider,
finished timelines also export over OTLP — with zero hard dependency on
either.
"""

from .propagation import (
    TRACEPARENT_HEADER,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from .spine import (
    NULL_TRACE,
    NullTrace,
    RequestTrace,
    Span,
    TraceStore,
    mono_to_epoch,
)

__all__ = [
    "TRACEPARENT_HEADER",
    "format_traceparent",
    "parse_traceparent",
    "new_trace_id",
    "new_span_id",
    "Span",
    "RequestTrace",
    "NullTrace",
    "NULL_TRACE",
    "TraceStore",
    "mono_to_epoch",
]
