"""In-process span store: the request timelines behind /debug/requests.

Design constraints, in order:

- **Zero hard deps, bounded memory.** Finished timelines live in a ring
  buffer (`deque(maxlen=capacity)`); in-flight timelines are capped too
  (a flood of never-finished requests must not grow the store without
  bound — overflow evicts the oldest as "orphaned").
- **Near-zero cost when disabled.** `TraceStore.start` on a disabled
  store returns the NULL_TRACE singleton whose every method is a no-op —
  instrumentation call sites never branch on an `if tracing:` guard and
  the disabled path allocates nothing per request.
- **Thread-safe.** The engine records from the step thread and HTTP
  executor threads while /debug/requests reads from the event loop; the
  store lock covers only membership (start/finish/query), and per-trace
  mutation is append-only from the request's own execution context.

Timestamps are epoch seconds (`time.time()`), the unit dashboards and
OTLP speak; `mono_to_epoch` converts the engine's `time.monotonic()`
request stamps without assuming the two clocks share an origin.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .propagation import (
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)


def mono_to_epoch(mono: float) -> float:
    """Epoch time of a time.monotonic() stamp taken in this process."""
    return time.time() - (time.monotonic() - mono)


class Span:
    """One named time window with attributes and point-in-time events."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start", "end", "status", "attrs", "events",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: str | None = None,
        start: float | None = None,
        attrs: dict | None = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start = time.time() if start is None else start
        self.end: float | None = None
        self.status = "ok"
        self.attrs: dict = dict(attrs) if attrs else {}
        self.events: list[tuple[float, str, dict]] = []

    # per-span event bound: a 4k-token stream emits a decode_window event
    # per resolved window — cap the list so one long request can't bloat
    # its ring slot (the final marker says truncation happened)
    MAX_EVENTS = 256

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def event(self, name: str, **attrs) -> None:
        n = len(self.events)
        if n >= self.MAX_EVENTS:
            if n == self.MAX_EVENTS:
                self.events.append((time.time(), "events_truncated", {}))
            return
        self.events.append((time.time(), name, attrs))

    def finish(self, end: float | None = None, status: str | None = None) -> None:
        if self.end is None:
            self.end = time.time() if end is None else end
        if status is not None:
            self.status = status

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.time()) - self.start

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_ms": round(self.duration * 1e3, 3),
            "status": self.status,
            "attrs": self.attrs,
            "events": [
                {"t": t, "name": n, **({"attrs": a} if a else {})}
                for t, n, a in self.events
            ],
        }


class RequestTrace:
    """One request's timeline: a root span plus flat child spans. Children
    parent to the root by default — deep nesting buys nothing for a
    request lifecycle, and a flat list renders directly as a timeline."""

    __slots__ = ("rid", "root", "spans", "_finished")

    def __init__(self, rid: str, root: Span):
        self.rid = rid
        self.root = root
        self.spans: list[Span] = []
        self._finished = False

    @property
    def trace_id(self) -> str:
        return self.root.trace_id

    def set(self, **attrs) -> None:
        self.root.set(**attrs)

    def event(self, name: str, **attrs) -> None:
        self.root.event(name, **attrs)

    def span(
        self,
        name: str,
        start: float | None = None,
        end: float | None = None,
        **attrs,
    ) -> Span:
        """Add a child span; pass explicit start/end to record a window
        measured elsewhere (the engine's phase attribution reconstructs
        queue/prefill/decode windows from request-carried stamps)."""
        s = Span(
            name, self.root.trace_id, parent_id=self.root.span_id,
            start=start, attrs=attrs or None,
        )
        if end is not None:
            s.finish(end=end)
        self.spans.append(s)
        return s

    def child_traceparent(self) -> str:
        """The traceparent to stamp on an outbound hop: this trace, with
        the root (ingress) span as the remote parent."""
        return format_traceparent(self.root.trace_id, self.root.span_id)

    @property
    def duration(self) -> float:
        return self.root.duration

    def to_dict(self) -> dict:
        return {
            "rid": self.rid,
            "trace_id": self.trace_id,
            "duration_ms": round(self.duration * 1e3, 3),
            "status": self.root.status,
            "spans": [self.root.to_dict()] + [s.to_dict() for s in self.spans],
        }


class NullTrace:
    """No-op stand-in returned by a disabled store: every recording call
    vanishes, so instrumentation sites need no enabled-checks."""

    rid = ""
    trace_id = ""
    duration = 0.0
    _finished = True

    def set(self, **attrs) -> None:
        pass

    def event(self, name: str, **attrs) -> None:
        pass

    def span(self, name, start=None, end=None, **attrs) -> "NullTrace":
        return self

    def finish(self, end=None, status=None) -> None:
        pass

    def child_traceparent(self) -> None:
        return None

    def to_dict(self) -> dict:
        return {}


NULL_TRACE = NullTrace()


class TraceStore:
    """Ring-buffer-bounded home of request timelines for one process."""

    # in-flight overflow factor: a flood of requests that never finish
    # (or a leak) evicts the oldest in-flight timeline once the in-flight
    # set reaches this multiple of the finished ring's capacity
    INFLIGHT_FACTOR = 2

    def __init__(
        self,
        capacity: int = 512,
        enabled: bool = True,
        service: str = "tpu-stack",
        otel_sink=None,
    ):
        self.enabled = enabled
        self.capacity = max(1, int(capacity))
        self.service = service
        self._lock = threading.Lock()
        self._ring: deque[RequestTrace] = deque(maxlen=self.capacity)
        self._inflight: dict[str, RequestTrace] = {}
        self.started_total = 0
        self.dropped_inflight_total = 0
        # OTLP bridge: resolved lazily on first finish unless injected
        # (tracing/otel.py) — None means "not resolved yet"
        self._otel_sink = otel_sink
        self._otel_resolved = otel_sink is not None

    # -- recording ---------------------------------------------------------

    def start(
        self,
        rid: str,
        name: str,
        traceparent: str | None = None,
        attrs: dict | None = None,
    ) -> RequestTrace | NullTrace:
        """Open a request timeline. A valid caller traceparent keeps its
        trace id (this root becomes a child of the caller's span); a
        missing/malformed one starts a fresh trace."""
        if not self.enabled:
            return NULL_TRACE
        ctx = parse_traceparent(traceparent)
        trace_id = ctx[0] if ctx else new_trace_id()
        root = Span(name, trace_id, parent_id=ctx[1] if ctx else None,
                    attrs=attrs)
        root.set(rid=rid, service=self.service)
        trace = RequestTrace(rid, root)
        with self._lock:
            self.started_total += 1
            if (
                len(self._inflight)
                >= self.capacity * self.INFLIGHT_FACTOR
            ):
                # dict insertion order IS start order (traces are inserted
                # at creation), so the oldest is the first key — O(1),
                # which matters because this path runs on every start()
                # during exactly the flood it guards against
                oldest = next(iter(self._inflight))
                orphan = self._inflight.pop(oldest)
                orphan.root.finish(status="orphaned")
                orphan._finished = True
                self._ring.append(orphan)
                self.dropped_inflight_total += 1
            # same-rid collision (two concurrent requests reusing one
            # client-supplied X-Request-Id): the newer trace takes the
            # in-flight slot; the displaced one still files into the ring
            # on finish (identity-checked pop below)
            self._inflight[rid] = trace
        return trace

    def finish(self, trace, status: str = "ok") -> None:
        """Close a timeline and move it into the finished ring. Idempotent
        (refusal paths may finish explicitly, then again in a finally)."""
        if trace is NULL_TRACE or not isinstance(trace, RequestTrace):
            return
        if trace._finished:
            return
        trace._finished = True
        trace.root.finish(status=status)
        with self._lock:
            # identity-checked: finishing trace A must not evict a
            # concurrent trace B that reused the same client-supplied rid
            if self._inflight.get(trace.rid) is trace:
                del self._inflight[trace.rid]
            self._ring.append(trace)
        self._export(trace)

    # -- queries (/debug/requests) -----------------------------------------

    def get(self, rid: str) -> RequestTrace | None:
        with self._lock:
            t = self._inflight.get(rid)
            if t is not None:
                return t
            for t in self._ring:
                if t.rid == rid:
                    return t
        return None

    def debug_response(self, query) -> tuple[dict, int]:
        """(payload, http_status) for a /debug/requests query mapping —
        the ONE place the rid/n parsing and 404 shaping live, so the
        router's and the engine's endpoints cannot diverge."""
        rid = query.get("rid")
        try:
            n = max(1, min(200, int(query.get("n", "20"))))
        except ValueError:
            n = 20
        payload = self.debug_payload(rid=rid, n=n)
        return payload, 404 if "error" in payload else 200

    def debug_payload(self, rid: str | None = None, n: int = 20) -> dict:
        """The /debug/requests JSON: one full trace for ?rid=, else the
        recent / slowest / in-flight summaries."""
        if rid is not None:
            t = self.get(rid)
            if t is None:
                return {"error": f"no trace for rid {rid!r}", "rid": rid}
            return t.to_dict()
        with self._lock:
            ring = list(self._ring)
            inflight = list(self._inflight.values())

        def brief(t: RequestTrace) -> dict:
            return {
                "rid": t.rid,
                "trace_id": t.trace_id,
                "status": t.root.status,
                "start": t.root.start,
                "duration_ms": round(t.duration * 1e3, 3),
                "spans": len(t.spans) + 1,
            }

        slowest = sorted(ring, key=lambda t: t.duration, reverse=True)
        return {
            "service": self.service,
            "enabled": self.enabled,
            "capacity": self.capacity,
            "started_total": self.started_total,
            "finished_buffered": len(ring),
            "inflight": [brief(t) for t in inflight[:n]],
            "recent": [brief(t) for t in ring[-n:]][::-1],
            "slowest": [brief(t) for t in slowest[:n]],
        }

    # -- OTLP bridge -------------------------------------------------------

    def _export(self, trace: RequestTrace) -> None:
        if not self._otel_resolved:
            from .otel import resolve_otel_sink

            self._otel_sink = resolve_otel_sink(self.service)
            self._otel_resolved = True
        if self._otel_sink is not None:
            try:
                self._otel_sink(trace)
            except Exception:
                # export is best-effort by contract: one bad span must not
                # fail requests, and a broken SDK install disables export
                self._otel_sink = None
