"""Router application: state wiring + HTTP surface.

The aiohttp equivalent of the reference's FastAPI assembly (app.py:83-300 +
routers/main_router.py:50-246). All components hang off one `RouterState`
object owned by the app — construction order and reconfiguration are explicit
functions, not singleton side effects.

HTTP surface (reference parity):
  POST /v1/chat/completions /v1/completions /v1/embeddings /v1/rerank
       /v1/score /tokenize /detokenize      — routed proxy
  GET  /v1/models                           — aggregated engine models
  GET  /v1/files/... POST /v1/files         — files service (files.py)
  POST /v1/batches ...                      — batch API (batch.py)
  GET  /health /metrics /engines /version
  POST /sleep /wake_up   GET /is_sleeping   — engine capacity levers
"""

from __future__ import annotations

import asyncio
import hmac
import json
import time
import uuid

from aiohttp import web

from ..qos import TenantTable
from ..qos.gate import TENANT_REQUEST_KEY, QoSGate
from ..tracing import TraceStore
from ..utils.logging import init_logger
from ..utils.tokenizer import hashing_tokenizer
from .breaker import BreakerBoard
from .callbacks import load_callbacks
from .discovery import make_discovery
from .dynamic_config import DynamicConfigWatcher
from .engine_stats import EngineStatsScraper
from .feature_gates import FeatureGates
from .metrics import RouterMetrics
from .request_service import RID_KEY, RequestService
from .request_stats import RequestStatsMonitor
from .rewriter import make_rewriter
from .routing import make_policy
from .args import parse_args

logger = init_logger(__name__)
VERSION = "0.2.0"


class RouterState:
    """Everything the handlers touch. Swappable members (discovery, policy)
    are replaced atomically by apply_dynamic_config."""

    def __init__(self, args):
        self.args = args
        self.discovery = make_discovery(**_discovery_kwargs(args))
        self.policy = make_policy(args.routing_logic, **_policy_kwargs(vars(args)))
        self.request_monitor = RequestStatsMonitor(args.request_stats_window)
        self.engine_scraper = EngineStatsScraper(
            # lambda-style indirection: the scraper must follow discovery swaps
            _DiscoveryProxy(self),
            args.engine_stats_interval,
        )
        self.metrics = RouterMetrics()
        # request-tracing spine (docs/28-request-tracing.md): per-request
        # span timelines (routing decision, failover attempts, QoS
        # verdicts, upstream TTFB) served by /debug/requests and joined
        # to the engines' spans via the propagated traceparent header
        self.traces = TraceStore(
            capacity=getattr(args, "trace_buffer", 512),
            enabled=getattr(args, "request_tracing", "on") != "off",
            service="tpu-router",
        )
        # per-endpoint circuit breakers (router/breaker.py): consecutive
        # upstream failures exclude an endpoint from policy picks until a
        # half-open probe succeeds
        self.breakers = BreakerBoard(
            failure_threshold=getattr(args, "breaker_failure_threshold", 5),
            cooldown_s=getattr(args, "breaker_cooldown_s", 5.0),
            max_cooldown_s=getattr(args, "breaker_max_cooldown_s", 120.0),
        )
        self.request_service = RequestService(self)
        self.feature_gates = FeatureGates(args.feature_gates)
        self.rewriter = make_rewriter(args.request_rewriter)
        self.callbacks = load_callbacks(args.callbacks)
        self.model_aliases: dict[str, str] = (
            json.loads(args.model_aliases) if args.model_aliases else {}
        )
        # multi-tenant QoS (docs/27-multitenancy.md): per-tenant identity,
        # rate limits, and priority stamping. None = QoS off (the historic
        # single-key behavior, zero overhead).
        self.qos: QoSGate | None = None
        if getattr(args, "tenant_table_file", None):
            self.qos = QoSGate(
                TenantTable.load(args.tenant_table_file),
                tokenizer=hashing_tokenizer(
                    getattr(args, "qos_tokenizer", "byte")
                ),
            )
        self.dynamic_config: DynamicConfigWatcher | None = None
        # fleet-coherence reporter (docs/32-fleet-telemetry.md): periodic
        # replica reports to the controller's /fleet/report; started in
        # on_startup when --fleet-report-url (or --kv-controller-url) and
        # a non-zero interval are configured
        self.fleet_reporter = None
        # event-loop starvation probe (docs/37-flight-recorder.md):
        # started in on_startup, exported as
        # tpu:router_event_loop_lag_seconds by RouterMetrics
        self.loop_lag_probe = None
        self.semantic_cache = None
        self.pii_middleware = None
        self.batch_service = None
        self.files = None
        self.started_at = time.time()
        # endpoint churn -> policy state (prefix trie scrub, session ring
        # sync, embedded KV index removal). Reads self.policy at fire time,
        # so dynamic policy swaps need no re-subscription.
        self.discovery.add_listener(self._on_endpoint_churn)

    def _on_endpoint_churn(self, removed: set, current: set) -> None:
        self.policy.on_endpoints_changed(removed, current)
        # endpoints discovery dropped must not leak breaker state —
        # discovery exclusion supersedes the breaker anyway, and a pod
        # recreated on the same URL deserves a clean one. The breaker's
        # real prey (endpoints that pass health probes but fail requests)
        # stays in `current` and keeps its history.
        self.breakers.prune(current)

    async def apply_dynamic_config(self, config: dict) -> None:
        """Hot-swap discovery/routing from a dynamic config dict."""
        # validate the tenant table FIRST: a malformed table must reject
        # the whole reload before any other key mutates state (and the
        # previous table keeps serving — TenantTable raises on bad input)
        new_table = (
            TenantTable.from_dict(config["tenants"])
            if "tenants" in config
            else None
        )
        if "model_aliases" in config:
            self.model_aliases = dict(config["model_aliases"])
        if any(k.startswith("static") or k == "service_discovery" for k in config):
            merged = dict(vars(self.args))
            merged.update(config)
            ns = _ArgsView(merged)
            new = make_discovery(**_discovery_kwargs(ns))
            new.add_listener(self._on_endpoint_churn)
            old, self.discovery = self.discovery, new
            await new.start()
            await old.stop()
        if "routing_logic" in config:
            merged = {**vars(self.args), **config}
            old_policy = self.policy
            self.policy = make_policy(
                config["routing_logic"], **_policy_kwargs(merged)
            )
            await old_policy.close()
        if new_table is not None:
            self.apply_tenant_table(new_table)

    def apply_tenant_table(self, table: TenantTable) -> None:
        """Swap the tenant policy table in place (dynamic-config reload or
        a change to --tenant-table-file). Limiter bucket levels survive for
        tenants present in both tables; creating the gate on first use
        lets a previously-QoS-less router adopt a table at runtime."""
        if self.qos is None:
            self.qos = QoSGate(
                table,
                tokenizer=hashing_tokenizer(
                    getattr(self.args, "qos_tokenizer", "byte")
                ),
            )
        else:
            self.qos.update_table(table)


class _ArgsView:
    def __init__(self, d: dict):
        self.__dict__.update(d)


class _DiscoveryProxy:
    """Lets long-lived components read the *current* discovery through state."""

    def __init__(self, state: RouterState):
        self._state = state

    def endpoints(self):
        return self._state.discovery.endpoints()


def _discovery_kwargs(args) -> dict:
    kw: dict = {"kind": args.service_discovery}
    if args.service_discovery == "static":
        kw["urls"] = [u.strip() for u in args.static_backends.split(",")]
        if getattr(args, "static_models", None):
            kw["models"] = [
                [m.strip() for m in group.split(",") if m.strip()]
                for group in args.static_models.split(";")
            ]
        if getattr(args, "static_model_labels", None):
            kw["model_labels"] = [
                x.strip() for x in args.static_model_labels.split(",")
            ]
        kw["probe_interval"] = getattr(args, "health_probe_interval", None)
    else:
        kw["k8s"] = {
            "namespace": args.k8s_namespace,
            "label_selector": args.k8s_label_selector,
            "port": args.k8s_port,
        }
    return kw


def _policy_kwargs(d: dict) -> dict:
    split = lambda v: [x.strip() for x in v.split(",")] if isinstance(v, str) else (v or [])  # noqa: E731
    return {
        "session_key": d.get("session_key") or "",
        "kv_controller_url": d.get("kv_controller_url") or "",
        "kv_aware_threshold": d.get("kv_aware_threshold", 256),
        "kv_index_mode": d.get("kv_index_mode") or "controller",
        "kv_index_tokenizer": d.get("kv_index_tokenizer") or "",
        "kv_migrate_scoring": d.get("kv_migrate_scoring") or "off",
        "prefill_model_labels": split(d.get("prefill_model_labels")),
        "decode_model_labels": split(d.get("decode_model_labels")),
    }


# -- handlers ---------------------------------------------------------------


def _state(request: web.Request) -> RouterState:
    return request.app["state"]


# everything that proxies to or controls engines requires the API key;
# /health /metrics /version stay open for probes and scrapers. The embedded
# KV-index routes mutate routing state (an unauthenticated /kv/events
# snapshot could steer matched traffic anywhere), so engines publishing to
# a keyed router must send the same bearer key (KV_CONTROLLER_API_KEY on
# the engine side).
_PROTECTED_PREFIXES = ("/v1", "/tokenize", "/detokenize")
_PROTECTED_EXACT = (
    "/sleep", "/wake_up", "/is_sleeping", "/engines",
    "/kv/events", "/register", "/deregister",
)


def _unauthorized() -> web.Response:
    return web.json_response(
        {"error": {"message": "invalid API key", "type": "auth_error"}},
        status=401,
    )


# request paths whose completions land in the structured access log (probe
# endpoints would flood it — /health, /metrics, /ready poll every few
# seconds; their failures still log via the status>=400 clause below)
_ACCESS_LOGGED_PREFIXES = ("/v1", "/tokenize", "/detokenize")


@web.middleware
async def request_id_middleware(request: web.Request, handler):
    """Outermost middleware: every response — including 401s from the auth
    middleware, tenant-throttle 429s, shed 429s, and breaker-exhausted
    503s — carries an `X-Request-Id` (echoed from the caller or generated
    here), and API-path completions emit one structured access-log line
    keyed on it. Error short-circuits used to return with no correlation
    id at all, making them the one class of response a caller could not
    report usefully."""
    rid = request.headers.get("X-Request-Id") or uuid.uuid4().hex
    # the same slot request_service uses — the proxy wrapper reuses this
    # id for its trace and upstream stamp instead of minting another
    request[RID_KEY] = rid
    t0 = time.monotonic()
    try:
        resp = await handler(request)
    except web.HTTPException as e:
        e.headers.setdefault("X-Request-Id", rid)
        raise
    if not resp.prepared:
        # streamed responses already sent their headers (stamped by the
        # proxy path before prepare); everything else is stamped here
        resp.headers.setdefault("X-Request-Id", rid)
    if (
        request.path.startswith(_ACCESS_LOGGED_PREFIXES)
        or resp.status >= 400
    ):
        tenant = request.get(TENANT_REQUEST_KEY)
        logger.info(
            "access rid=%s method=%s path=%s status=%d dur_ms=%.1f%s",
            rid, request.method, request.path, resp.status,
            (time.monotonic() - t0) * 1e3,
            f" tenant={tenant.tenant_id}" if tenant is not None else "",
        )
    return resp


@web.middleware
async def auth_middleware(request: web.Request, handler):
    """Bearer auth + tenant resolution. Every comparison is
    hmac.compare_digest — the old `auth != f"Bearer {key}"` check leaked
    the match length through timing. With a tenant table (state.qos) the
    key identifies the CALLER, not just validity: the resolved policy
    rides on the request for the QoS gate and the upstream stamp."""
    state = _state(request)
    key = state.args.api_key
    qos = state.qos
    needs_auth = request.path.startswith(_PROTECTED_PREFIXES) or (
        request.path in _PROTECTED_EXACT
    )
    if needs_auth and (key or qos is not None):
        auth = request.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else None
        tenant = (
            qos.resolve_tenant(token, request.headers)
            if qos is not None
            else None
        )
        # a tenant matched by its OWN api_key authenticates; a keyless row
        # claimed via the x-tenant-id header only selects identity (mTLS-
        # style deployments trust the header upstream) and must NOT bypass
        # a configured global key
        authed_by_tenant_key = tenant is not None and bool(tenant.api_key)
        if not authed_by_tenant_key:
            # the global key (→ default tenant) still authenticates; with
            # no global key, a PRESENTED-but-unknown token is refused (a
            # typo'd tenant key must not silently serve on the default
            # tier) while bare requests stay open. Bytes compares: a
            # non-ASCII token must 401, not TypeError→500.
            if key:
                if not (
                    token
                    and hmac.compare_digest(
                        token.encode("utf-8", "surrogateescape"),
                        key.encode("utf-8", "surrogateescape"),
                    )
                ):
                    return _unauthorized()
            elif token is not None and qos is not None and qos.table.has_keys():
                return _unauthorized()
        if qos is not None:
            request[TENANT_REQUEST_KEY] = tenant or qos.table.default_policy
    return await handler(request)


async def handle_openai(request: web.Request) -> web.StreamResponse:
    state = _state(request)
    if state.pii_middleware is not None:
        blocked = await state.pii_middleware.check(request)
        if blocked is not None:
            return blocked
    if state.semantic_cache is not None and request.path == "/v1/chat/completions":
        hit = await state.semantic_cache.lookup(request)
        if hit is not None:
            return hit
    resp = await state.request_service.route_openai_request(request)
    return resp


async def handle_models(request: web.Request) -> web.Response:
    state = _state(request)
    seen: dict[str, dict] = {}
    for ep in state.discovery.endpoints():
        for name in ep.model_names:
            info = ep.model_info.get(name)
            card = {
                "id": name,
                "object": "model",
                "created": info.created if info else int(ep.added_at),
                "owned_by": info.owned_by if info else "tpu-stack",
            }
            if info and info.parent:
                card["parent"] = info.parent
                card["root"] = info.root
            seen.setdefault(name, card)
    for alias, target in state.model_aliases.items():
        if target in seen and alias not in seen:
            seen[alias] = {**seen[target], "id": alias}
    return web.json_response({"object": "list", "data": list(seen.values())})


async def handle_engines(request: web.Request) -> web.Response:
    state = _state(request)
    engine_stats = state.engine_scraper.get_engine_stats()
    request_stats = state.request_monitor.get_request_stats()
    breakers = state.breakers.snapshot()
    out = []
    for ep in state.discovery.endpoints():
        entry = ep.to_dict()
        es = engine_stats.get(ep.url)
        rs = request_stats.get(ep.url)
        entry["engine_stats"] = es.__dict__ if es else None
        entry["request_stats"] = rs.__dict__ if rs else None
        entry["breaker"] = breakers.get(ep.url)
        out.append(entry)
    return web.json_response({"engines": out})


async def handle_health(request: web.Request) -> web.Response:
    state = _state(request)
    problems = []
    if not state.discovery.is_healthy():
        problems.append("service discovery watcher is down")
    if not state.engine_scraper.is_healthy():
        problems.append("engine stats scraper is down")
    body = {
        "status": "unhealthy" if problems else "ok",
        "problems": problems,
        "version": VERSION,
        "uptime": time.time() - state.started_at,
    }
    if state.dynamic_config is not None:
        body["dynamic_config"] = {
            "reloads": state.dynamic_config.reload_count,
            "current": state.dynamic_config.current,
        }
    return web.json_response(body, status=503 if problems else 200)


async def handle_metrics(request: web.Request) -> web.Response:
    state = _state(request)
    from ..engine.metrics import OPENMETRICS_CONTENT_TYPE, wants_openmetrics

    if wants_openmetrics(request):
        # the exposition that renders exemplars (trace ids on the
        # tpu:request_* histogram buckets); explicit opt-in only — see
        # engine.metrics.wants_openmetrics on why not Accept-negotiated
        return web.Response(
            body=state.metrics.render(state, openmetrics=True),
            headers={"Content-Type": OPENMETRICS_CONTENT_TYPE},
        )
    return web.Response(
        body=state.metrics.render(state),
        content_type="text/plain",
        charset="utf-8",
    )


# one-liner per mounted debug endpoint — the GET /debug index (the
# engine serves the same shape; docs/37-flight-recorder.md)
ROUTER_DEBUG_ENDPOINTS = {
    "GET /debug": "this index",
    "GET /debug/requests": "tracing-spine timelines; ?rid= one full trace "
                           "(docs/28)",
    "GET /debug/fleet": "ring membership, embedded index positions, "
                        "breakers, budget scale, last fleet reply "
                        "(docs/32/34)",
    "GET /debug/loop": "asyncio event-loop lag probe state (docs/37)",
}


async def handle_debug_index(request: web.Request) -> web.Response:
    """GET /debug: every mounted debug endpoint with a one-liner."""
    return web.json_response({"endpoints": ROUTER_DEBUG_ENDPOINTS})


async def handle_debug_loop(request: web.Request) -> web.Response:
    """Event-loop lag probe introspection (docs/37-flight-recorder.md)."""
    probe = _state(request).loop_lag_probe
    return web.json_response(
        probe.snapshot() if probe is not None else {"enabled": False}
    )


async def handle_debug_requests(request: web.Request) -> web.Response:
    """Tracing-spine introspection (docs/28-request-tracing.md): recent /
    slowest / in-flight request timelines; ?rid= returns one full trace."""
    payload, status = _state(request).traces.debug_response(request.query)
    return web.json_response(payload, status=status)


async def handle_debug_fleet(request: web.Request) -> web.Response:
    """Fleet-coherence introspection (docs/32-fleet-telemetry.md): this
    replica's ring membership hash, embedded KV-index seq positions +
    convergence lag, breaker states, in-flight streams, and the last
    fleet-view reply from the controller (index divergence, fleet tenant
    utilization, ring-divergence flag)."""
    from .fleet import debug_fleet_snapshot

    return web.json_response(debug_fleet_snapshot(_state(request)))


async def handle_version(request: web.Request) -> web.Response:
    return web.json_response({"version": VERSION})


async def handle_kv_events(request: web.Request) -> web.Response:
    """Embedded-index mode: engines publish their KV events straight to the
    router (the router IS the cluster index subscriber — no controller hop
    anywhere). 409 when the active policy doesn't host an index."""
    state = _state(request)
    index = getattr(state.policy, "index", None)
    if index is None:
        return web.json_response(
            {"error": "router is not in embedded KV index mode"}, status=409
        )
    raw = await request.text()
    # off-loop: a resync snapshot parses a whole pool's hashes — both the
    # multi-MB json.loads and the hex walk must not stall concurrent
    # route()/proxy work (ClusterKVIndex is thread-safe; the lock is held
    # only for the set swap)
    reply = await asyncio.get_running_loop().run_in_executor(
        None, lambda: index.apply(json.loads(raw))
    )
    return web.json_response(reply)


async def handle_peer_lookup(request: web.Request) -> web.Response:
    """Peer-tier rediscovery against the EMBEDDED index (the controller
    serves the same shape, engine/kv_controller.py): which engine holds
    the longest consecutively-resident run of an already-hashed chain
    (docs/35-peer-kv-reuse.md). Engines whose KV_CONTROLLER_URL points at
    this router resolve peer owners here with zero controller hops."""
    state = _state(request)
    index = getattr(state.policy, "index", None)
    if index is None:
        return web.json_response(
            {"error": "router is not in embedded KV index mode"}, status=409
        )
    body = await request.json()
    raw = body.get("hashes")
    block_size = int(body.get("block_size") or 0)
    if not isinstance(raw, list) or block_size <= 0:
        return web.json_response(
            {"error": "hashes (hex list) and block_size are required"},
            status=400,
        )
    try:
        hashes = [int(h, 16) for h in raw]
    except (TypeError, ValueError):
        return web.json_response(
            {"error": "hashes must be hex strings"}, status=400
        )
    url, matched = index.lookup_hashes(
        hashes, block_size, exclude=body.get("exclude") or None
    )
    reply: dict = {"url": url, "matched_blocks": matched}
    if url:
        # transport hint (docs/39-device-peer-kv.md): same negotiation the
        # controller runs — "device" only when asker and owner advertised
        # the same mesh group at registration; omitted otherwise (absent
        # means HTTP, keeping pre-39 reply shapes byte-stable)
        from ..kv_index import negotiate_transport

        hint = negotiate_transport(
            body.get("transport"), index.get_transport(url)
        )
        if hint == "device":
            reply["transport"] = hint
    return web.json_response(reply)


async def handle_kv_register(request: web.Request) -> web.Response:
    """Engines POST /register|/deregister to KV_CONTROLLER_URL on startup
    and shutdown — accept both when that URL points at this router. The
    index itself treats publishing as registration; deregister drops the
    engine's slice immediately instead of waiting for discovery."""
    state = _state(request)
    index = getattr(state.policy, "index", None)
    if index is None:
        return web.json_response(
            {"error": "router is not in embedded KV index mode"}, status=409
        )
    body = await request.json()
    url = (body.get("url") or "").rstrip("/")
    if request.path == "/deregister" and url:
        index.remove_engine(url)
    elif url:
        # remember the engine's device-transport identity (mesh group +
        # process coords) so /peer_lookup replies can carry the hint;
        # falsy/absent clears — an engine restarted without a mesh must
        # not keep a stale "device" advertisement
        index.set_transport(url, body.get("transport"))
    return web.json_response({"status": "ok"})


async def handle_sleep(request: web.Request) -> web.Response:
    return await _state(request).request_service.sleep_control(request, "sleep")


async def handle_wake(request: web.Request) -> web.Response:
    return await _state(request).request_service.sleep_control(request, "wake_up")


async def handle_is_sleeping(request: web.Request) -> web.Response:
    return await _state(request).request_service.sleep_control(
        request, "is_sleeping"
    )


# -- assembly ---------------------------------------------------------------

OPENAI_PROXY_PATHS = (
    "/v1/chat/completions",
    "/v1/completions",
    "/v1/embeddings",
    "/v1/rerank",
    "/v1/score",
    "/tokenize",
    "/detokenize",
    "/v1/audio/transcriptions",
)


def build_app(args) -> web.Application:
    state = RouterState(args)
    # request_id_middleware OUTERMOST: auth 401s and every other
    # short-circuit must still come back stamped with X-Request-Id
    app = web.Application(
        middlewares=[request_id_middleware, auth_middleware],
        client_max_size=64 * 2**20,
    )
    app["state"] = state

    for path in OPENAI_PROXY_PATHS:
        app.router.add_post(path, handle_openai)
    app.router.add_get("/v1/models", handle_models)
    app.router.add_get("/engines", handle_engines)
    app.router.add_get("/health", handle_health)
    app.router.add_get("/metrics", handle_metrics)
    app.router.add_get("/debug", handle_debug_index)
    app.router.add_get("/debug/requests", handle_debug_requests)
    app.router.add_get("/debug/fleet", handle_debug_fleet)
    app.router.add_get("/debug/loop", handle_debug_loop)
    app.router.add_get("/version", handle_version)
    app.router.add_post("/sleep", handle_sleep)
    app.router.add_post("/wake_up", handle_wake)
    app.router.add_get("/is_sleeping", handle_is_sleeping)
    # embedded cluster-KV-index surface (active when the kvaware policy
    # hosts the index; registered unconditionally because dynamic config
    # can swap the policy after the route table froze)
    app.router.add_post("/kv/events", handle_kv_events)
    app.router.add_post("/peer_lookup", handle_peer_lookup)
    app.router.add_post("/register", handle_kv_register)
    app.router.add_post("/deregister", handle_kv_register)

    if args.enable_batch_api:
        from .batch import BatchService
        from .files import FileStorage

        state.files = FileStorage(args.files_dir)
        state.batch_service = BatchService(args.batch_db, state)
        state.files.register_routes(app)
        state.batch_service.register_routes(app)

    if state.feature_gates.enabled("SemanticCache") and args.semantic_cache_dir:
        from .semantic_cache import SemanticCache

        state.semantic_cache = SemanticCache(
            args.semantic_cache_dir, args.semantic_cache_threshold,
            state=state,
        )
    if state.feature_gates.enabled("PIIDetection"):
        from .pii import PIIMiddleware, make_analyzer

        state.pii_middleware = PIIMiddleware(
            analyzer=make_analyzer(getattr(args, "pii_analyzer", "regex"))
        )

    async def on_startup(app):
        await state.request_service.start()
        await state.discovery.start()
        await state.engine_scraper.start()
        lag_interval = getattr(args, "event_loop_lag_interval_s", 0.5)
        if lag_interval and lag_interval > 0:
            from ..engine.flightrec import EventLoopLagProbe

            state.loop_lag_probe = EventLoopLagProbe(lag_interval)
            state.loop_lag_probe.start()
        fleet_url = getattr(args, "fleet_report_url", None) or getattr(
            args, "kv_controller_url", None
        )
        if fleet_url and getattr(args, "fleet_report_interval", 0) > 0:
            from .fleet import FleetReporter

            state.fleet_reporter = FleetReporter(
                state, fleet_url,
                interval_s=args.fleet_report_interval,
                replica_id=getattr(args, "router_replica_id", "") or "",
                budget_scaling=(
                    getattr(args, "fleet_budget_scaling", "on") != "off"
                ),
            )
            await state.fleet_reporter.start()
        if state.batch_service is not None:
            await state.batch_service.start()
        if args.dynamic_config_file or getattr(
            args, "tenant_table_file", None
        ):
            # one watcher covers both the dynamic config AND the tenant
            # table file — a router started with only --tenant-table-file
            # still hot-reloads table edits
            state.dynamic_config = DynamicConfigWatcher(
                args.dynamic_config_file, state, args.dynamic_config_interval,
                tenant_table_path=getattr(args, "tenant_table_file", None),
            )
            await state.dynamic_config.start()
        if args.log_stats_interval > 0:
            app["log_stats_task"] = asyncio.create_task(
                _log_stats_loop(state, args.log_stats_interval)
            )

    async def on_cleanup(app):
        task = app.get("log_stats_task")
        if task:
            task.cancel()
        if state.loop_lag_probe is not None:
            await state.loop_lag_probe.stop()
        if state.fleet_reporter is not None:
            await state.fleet_reporter.stop()
        if state.dynamic_config is not None:
            await state.dynamic_config.stop()
        if state.batch_service is not None:
            await state.batch_service.stop()
        await state.engine_scraper.stop()
        await state.discovery.stop()
        await state.policy.close()
        await state.request_service.stop()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


async def _log_stats_loop(state: RouterState, interval: float) -> None:
    while True:
        await asyncio.sleep(interval)
        req = state.request_monitor.get_request_stats()
        eng = state.engine_scraper.get_engine_stats()
        for ep in state.discovery.endpoints():
            r, e = req.get(ep.url), eng.get(ep.url)
            logger.info(
                "stats %s qps=%.2f ttft=%.3fs running=%s queued=%s kv=%.1f%%",
                ep.url,
                r.qps if r else 0.0,
                r.ttft if r else 0.0,
                int(e.num_running_requests) if e else "?",
                int(e.num_queuing_requests) if e else "?",
                (e.hbm_kv_usage_perc * 100) if e else 0.0,
            )


def main(argv: list[str] | None = None) -> None:
    args = parse_args(argv)
    from ..utils.system import raise_fd_limit
    from .tracing import init_otel, init_sentry

    # one upstream + one downstream socket per in-flight stream: the 1024
    # default exhausts far below serving concurrency (ref utils.py:132-147)
    raise_fd_limit()

    # process-global, once: re-init per build_app would stack OTel
    # providers/export threads (build_app runs per-test in the suite)
    init_sentry(args.sentry_dsn, args.sentry_traces_sample_rate)
    init_otel()
    app = build_app(args)
    logger.info(
        "router starting on %s:%d discovery=%s routing=%s",
        args.host,
        args.port,
        args.service_discovery,
        args.routing_logic,
    )
    web.run_app(app, host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
