"""Router-side Prometheus metrics.

Per-engine gauges refreshed from the stats monitors on each /metrics scrape
(pull-time refresh instead of the reference's push-from-logger-thread,
services/metrics_service/__init__.py + routers/metrics_router.py:42-123).
Engine-scraped prefix-cache numbers are re-exported so dashboards and the
prometheus-adapter can read everything from the router.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .. import metrics_contract as mc
from ..kv_index import LookupLatency

LABEL = ["server"]


class RouterMetrics:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        g = lambda name, doc: Gauge(  # noqa: E731
            name, doc, LABEL, registry=self.registry
        )
        self.current_qps = g("router_current_qps", "Arrival QPS per engine")
        self.avg_ttft = g("router_avg_ttft", "Avg time-to-first-token (s)")
        self.avg_latency = g("router_avg_latency", "Avg request latency (s)")
        self.in_prefill = g("router_num_prefill_requests", "Requests awaiting first byte")
        self.in_decoding = g("router_num_decoding_requests", "Requests streaming")
        self.finished = g("router_num_finished_requests", "Finished requests")
        self.engine_running = g(
            "router_engine_num_running_requests", "Engine-reported running requests"
        )
        self.engine_queuing = g(
            "router_engine_num_queuing_requests", "Engine-reported queued requests"
        )
        self.kv_usage = g(
            "router_engine_hbm_kv_usage_perc", "Engine-reported HBM KV usage fraction"
        )
        self.kv_hit_rate = g(
            "router_engine_prefix_cache_hit_rate", "Engine-reported prefix cache hit rate"
        )
        self.healthy_engines = Gauge(
            "router_healthy_engines_total",
            "Engines currently routable",
            registry=self.registry,
        )
        # per-endpoint circuit breakers (router/breaker.py). Monotonic
        # totals exported as gauges: the values are owned by the breaker
        # board and SET at scrape time (same convention as
        # CLUSTER_KV_EVENTS below).
        self.breaker_state = g(
            mc.ROUTER_BREAKER_STATE,
            "Breaker state per endpoint (0 closed, 1 half-open, 2 open)",
        )
        self.breaker_opens = g(
            mc.ROUTER_BREAKER_OPENS,
            "Times each endpoint's breaker opened",
        )
        self.upstream_failures = g(
            mc.ROUTER_UPSTREAM_FAILURES,
            "Upstream failures recorded against each endpoint",
        )
        # goodput signal path (docs/29-saturation-slo.md): streams torn
        # after headers went out (engine died mid-stream) — the requests
        # whose partial output the engine-side ledger can't see. Request-
        # level: the router proxies bytes and can't count token boundaries.
        self.severed_streams = Counter(
            mc.ROUTER_SEVERED_STREAMS[: -len("_total")],
            "Streams severed after headers (engine died mid-stream; the "
            "client saw a truncated transfer)",
            registry=self.registry,
        )
        # embedded cluster-KV-index (kvaware --kv-index-mode embedded):
        # contract names shared with the KV controller's /metrics
        # (metrics_contract.CLUSTER_KV_*), so dashboards key off ONE name
        # wherever the index lives
        self.kv_index_hashes = Gauge(
            mc.CLUSTER_KV_INDEX_HASHES,
            "Hashes in the embedded cluster KV index",
            registry=self.registry,
        )
        self.kv_index_engines = Gauge(
            mc.CLUSTER_KV_INDEX_ENGINES,
            "Engines publishing into the embedded cluster KV index",
            registry=self.registry,
        )
        self.kv_index_stale = Gauge(
            mc.CLUSTER_KV_INDEX_STALE_ENGINES,
            "Engines whose index slice awaits a resync (sequence gap)",
            registry=self.registry,
        )
        self.kv_index_events = Gauge(
            # monotonic, but exported as a gauge: the value is owned by the
            # index (set, not incremented, at scrape time)
            mc.CLUSTER_KV_EVENTS,
            "KV events applied to the embedded cluster index",
            registry=self.registry,
        )
        self.kv_index_resyncs = Gauge(
            mc.CLUSTER_KV_RESYNCS,
            "Resyncs requested from publishers (gap/epoch/overflow)",
            registry=self.registry,
        )
        self.kv_lookups = Counter(
            mc.CLUSTER_KV_LOOKUPS,
            "KV-aware lookups by mode",
            ["mode"],
            registry=self.registry,
        )
        self.kv_lookup_latency = Histogram(
            mc.CLUSTER_KV_LOOKUP_LATENCY,
            "KV-aware lookup latency by mode",
            ["mode"],
            # same boundaries wherever the index lives — dashboards key off
            # one metric name across controller and embedded deployments
            buckets=LookupLatency.BUCKETS,
            registry=self.registry,
        )
        # multi-tenant QoS (docs/27-multitenancy.md): the router's half of
        # the tpu:tenant_* contract — admitted traffic and per-tenant
        # throttles (429s that never reached an engine). Label cardinality
        # is bounded by the tenant table size.
        tc = lambda name, doc: Counter(  # noqa: E731
            name[: -len("_total")] if name.endswith("_total") else name,
            doc, ["tenant"], registry=self.registry,
        )
        self.tenant_requests = tc(
            mc.TENANT_REQUESTS, "Requests admitted through the QoS gate"
        )
        self.tenant_prompt_tokens = tc(
            mc.TENANT_PROMPT_TOKENS,
            "Prompt tokens metered through the QoS gate",
        )
        self.tenant_throttled = tc(
            mc.TENANT_THROTTLED,
            "Requests refused by per-tenant rate limits / concurrency caps",
        )
        self._tenant_series = {
            "requests": self.tenant_requests,
            "prompt_tokens": self.tenant_prompt_tokens,
            "throttled": self.tenant_throttled,
        }
        # per-request latency histograms (docs/28-request-tracing.md):
        # the ROUTER's vantage of the shared contract names — client-
        # visible TTFT/E2E including routing + proxy overhead (the engine
        # exports the same names plus queue/prefill/decode from its own
        # clock). Observed with trace-id exemplars at request finish.
        self.request_ttft = Histogram(
            mc.REQUEST_TTFT,
            "Request arrival at the router to first upstream byte",
            buckets=mc.REQUEST_PHASE_BUCKETS,
            registry=self.registry,
        )
        self.request_e2e = Histogram(
            mc.REQUEST_E2E,
            "Request arrival at the router to response completion",
            buckets=mc.REQUEST_PHASE_BUCKETS,
            registry=self.registry,
        )

    def observe_request(
        self,
        ttft: float | None,
        e2e: float,
        trace_id: str | None = None,
    ) -> None:
        """One served request's router-vantage latencies; the exemplar
        links a dashboard outlier straight to /debug/requests?rid=."""
        exemplar = {"trace_id": trace_id} if trace_id else None
        if ttft is not None:
            self.request_ttft.observe(max(0.0, ttft), exemplar=exemplar)
        self.request_e2e.observe(max(0.0, e2e), exemplar=exemplar)

    def _render_kv_index(self, policy) -> None:
        index = getattr(policy, "index", None)
        if index is not None:
            st = index.stats()
            self.kv_index_hashes.set(st["hashes"])
            self.kv_index_engines.set(st["engines"])
            self.kv_index_stale.set(st["stale_engines"])
            self.kv_index_events.set(st["events_applied"])
            self.kv_index_resyncs.set(st["resyncs_requested"])
        drain = getattr(policy, "drain_lookup_log", None)
        if drain is not None:
            for mode, seconds in drain():
                self.kv_lookups.labels(mode=mode).inc()
                self.kv_lookup_latency.labels(mode=mode).observe(seconds)

    def render(self, state, openmetrics: bool = False) -> bytes:
        self._render_kv_index(state.policy)
        qos = getattr(state, "qos", None)
        if qos is not None:
            for (tenant, kind), delta in qos.drain_counter_deltas().items():
                series = self._tenant_series.get(kind)
                if series is not None:
                    series.labels(tenant=tenant).inc(delta)
        req_stats = state.request_monitor.get_request_stats()
        for url, st in req_stats.items():
            self.current_qps.labels(server=url).set(st.qps)
            self.avg_ttft.labels(server=url).set(st.ttft)
            self.avg_latency.labels(server=url).set(st.latency)
            self.in_prefill.labels(server=url).set(st.in_prefill_requests)
            self.in_decoding.labels(server=url).set(st.in_decoding_requests)
            self.finished.labels(server=url).set(st.finished_requests)
        for url, snap in state.breakers.snapshot().items():
            self.breaker_state.labels(server=url).set(snap["state_code"])
            self.breaker_opens.labels(server=url).set(snap["opens_total"])
            self.upstream_failures.labels(server=url).set(
                snap["failures_total"]
            )
        for url, st in state.engine_scraper.get_engine_stats().items():
            self.engine_running.labels(server=url).set(st.num_running_requests)
            self.engine_queuing.labels(server=url).set(st.num_queuing_requests)
            self.kv_usage.labels(server=url).set(st.hbm_kv_usage_perc)
            self.kv_hit_rate.labels(server=url).set(st.prefix_cache_hit_rate)
        self.healthy_engines.set(
            sum(
                1
                for e in state.discovery.endpoints()
                if e.healthy and not e.sleeping
            )
        )
        if openmetrics:
            from prometheus_client.openmetrics import exposition as om

            return om.generate_latest(self.registry)
        return generate_latest(self.registry)
