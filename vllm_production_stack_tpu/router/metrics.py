"""Router-side Prometheus metrics.

Per-engine gauges refreshed from the stats monitors on each /metrics scrape
(pull-time refresh instead of the reference's push-from-logger-thread,
services/metrics_service/__init__.py + routers/metrics_router.py:42-123).
Engine-scraped prefix-cache numbers are re-exported so dashboards and the
prometheus-adapter can read everything from the router.
"""

from __future__ import annotations

import time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .. import metrics_contract as mc
from ..fleet import ConvergenceMeter
from ..kv_index import LookupLatency

LABEL = ["server"]


class RouterMetrics:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        g = lambda name, doc: Gauge(  # noqa: E731
            name, doc, LABEL, registry=self.registry
        )
        self.current_qps = g("router_current_qps", "Arrival QPS per engine")
        self.avg_ttft = g("router_avg_ttft", "Avg time-to-first-token (s)")
        self.avg_latency = g("router_avg_latency", "Avg request latency (s)")
        self.in_prefill = g("router_num_prefill_requests", "Requests awaiting first byte")
        self.in_decoding = g("router_num_decoding_requests", "Requests streaming")
        self.finished = g("router_num_finished_requests", "Finished requests")
        self.engine_running = g(
            "router_engine_num_running_requests", "Engine-reported running requests"
        )
        self.engine_queuing = g(
            "router_engine_num_queuing_requests", "Engine-reported queued requests"
        )
        self.kv_usage = g(
            "router_engine_hbm_kv_usage_perc", "Engine-reported HBM KV usage fraction"
        )
        self.kv_hit_rate = g(
            "router_engine_prefix_cache_hit_rate", "Engine-reported prefix cache hit rate"
        )
        self.healthy_engines = Gauge(
            "router_healthy_engines_total",
            "Engines currently routable",
            registry=self.registry,
        )
        # per-endpoint circuit breakers (router/breaker.py). Monotonic
        # totals exported as gauges: the values are owned by the breaker
        # board and SET at scrape time (same convention as
        # CLUSTER_KV_EVENTS below).
        self.breaker_state = g(
            mc.ROUTER_BREAKER_STATE,
            "Breaker state per endpoint (0 closed, 1 half-open, 2 open)",
        )
        self.breaker_opens = g(
            mc.ROUTER_BREAKER_OPENS,
            "Times each endpoint's breaker opened",
        )
        self.upstream_failures = g(
            mc.ROUTER_UPSTREAM_FAILURES,
            "Upstream failures recorded against each endpoint",
        )
        # goodput signal path (docs/29-saturation-slo.md): streams torn
        # after headers went out (engine died mid-stream) — the requests
        # whose partial output the engine-side ledger can't see. Request-
        # level: the router proxies bytes and can't count token boundaries.
        self.severed_streams = Counter(
            mc.ROUTER_SEVERED_STREAMS[: -len("_total")],
            "Streams severed after headers (engine died mid-stream; the "
            "client saw a truncated transfer)",
            registry=self.registry,
        )
        # embedded cluster-KV-index (kvaware --kv-index-mode embedded):
        # contract names shared with the KV controller's /metrics
        # (metrics_contract.CLUSTER_KV_*), so dashboards key off ONE name
        # wherever the index lives
        self.kv_index_hashes = Gauge(
            mc.CLUSTER_KV_INDEX_HASHES,
            "Hashes in the embedded cluster KV index",
            registry=self.registry,
        )
        self.kv_index_engines = Gauge(
            mc.CLUSTER_KV_INDEX_ENGINES,
            "Engines publishing into the embedded cluster KV index",
            registry=self.registry,
        )
        self.kv_index_stale = Gauge(
            mc.CLUSTER_KV_INDEX_STALE_ENGINES,
            "Engines whose index slice awaits a resync (sequence gap)",
            registry=self.registry,
        )
        self.kv_index_events = Gauge(
            # monotonic, but exported as a gauge: the value is owned by the
            # index (set, not incremented, at scrape time)
            mc.CLUSTER_KV_EVENTS,
            "KV events applied to the embedded cluster index",
            registry=self.registry,
        )
        self.kv_index_resyncs = Gauge(
            mc.CLUSTER_KV_RESYNCS,
            "Resyncs requested from publishers (gap/epoch/overflow)",
            registry=self.registry,
        )
        self.kv_lookups = Counter(
            mc.CLUSTER_KV_LOOKUPS,
            "KV-aware lookups by mode",
            ["mode"],
            registry=self.registry,
        )
        self.kv_lookup_latency = Histogram(
            mc.CLUSTER_KV_LOOKUP_LATENCY,
            "KV-aware lookup latency by mode",
            ["mode"],
            # same boundaries wherever the index lives — dashboards key off
            # one metric name across controller and embedded deployments
            buckets=LookupLatency.BUCKETS,
            registry=self.registry,
        )
        self.kv_replications = Counter(
            # the flash-crowd replication loop (docs/39-device-peer-kv.md)
            # lives in the KV controller, which renders the live series on
            # its /metrics; the embedded index has no replication loop, so
            # this stays 0 here — exported anyway so the name keeps one
            # home per deployment shape, like the rest of CLUSTER_KV_*
            mc.CLUSTER_KV_REPLICATIONS,
            "Flash-crowd prefix replications ordered by the cluster index",
            registry=self.registry,
        )
        # pool rebalancing (docs/40-pool-rebalancing.md): the flip state
        # machine lives in the KV controller, which hand-renders the live
        # series on its /metrics — these stay 0 here, exported so each
        # name keeps one registry home (the kv_replications convention)
        self.pool_rebalance_flips = Counter(
            mc.POOL_REBALANCE_FLIPS[: -len("_total")],
            "Finished pool-rebalance episodes by outcome (closed set: "
            + ", ".join(mc.POOL_REBALANCE_OUTCOME_VALUES) + ")",
            ["outcome"],
            registry=self.registry,
        )
        for outcome in mc.POOL_REBALANCE_OUTCOME_VALUES:
            self.pool_rebalance_flips.labels(outcome=outcome)
        self.pool_rebalance_phase = Gauge(
            mc.POOL_REBALANCE_PHASE,
            "Rebalancer state-machine phase (closed set: "
            + ", ".join(mc.POOL_REBALANCE_PHASE_VALUES)
            + "; 1 on the current phase)",
            ["phase"],
            registry=self.registry,
        )
        for phase in mc.POOL_REBALANCE_PHASE_VALUES:
            self.pool_rebalance_phase.labels(phase=phase).set(0)
        # priced route-vs-migrate (docs/35-peer-kv-reuse.md): per-request
        # verdicts once a prefix owner was found (closed decision set,
        # seeded at zero) — the router half of the peer-tier loop
        self.kv_migrate_decisions = Counter(
            mc.ROUTER_KV_MIGRATE_DECISIONS[: -len("_total")],
            "KV route-vs-migrate verdicts under --kv-migrate-scoring "
            "priced (closed set: "
            + ", ".join(mc.KV_MIGRATE_DECISION_VALUES)
            + ") — migrate = routed to the least-loaded engine with the "
            "owner hint stamped upstream for a peer pull",
            ["decision"],
            registry=self.registry,
        )
        for decision in mc.KV_MIGRATE_DECISION_VALUES:
            self.kv_migrate_decisions.labels(decision=decision)
        # fleet-coherence telemetry (docs/32-fleet-telemetry.md) ----------
        # subscriber-vantage convergence lag of the EMBEDDED index (the
        # controller renders the same name for its own index); drained
        # from kv_index.ConvergenceMeter so each observation lands once
        self.kv_convergence_lag = Histogram(
            mc.CLUSTER_KV_CONVERGENCE_LAG,
            "Publish-to-apply lag of KV event batches at this subscriber",
            buckets=ConvergenceMeter.BUCKETS,
            registry=self.registry,
        )
        self.kv_engine_seq = Gauge(
            mc.CLUSTER_KV_ENGINE_SEQ,
            "Applied KV event sequence position per publishing engine "
            "(embedded index)",
            ["engine"],
            registry=self.registry,
        )
        # labeled replica= like the controller's exposition, and so the
        # series can be CLEARED when the fleet reply goes stale — an
        # unlabeled gauge would freeze its last value through a
        # controller outage
        self.kv_index_divergence = Gauge(
            mc.CLUSTER_KV_INDEX_DIVERGENCE,
            "Estimated blocks by which this replica's embedded index "
            "diverges from the controller's authoritative one (from the "
            "/fleet/report reply)",
            ["replica"],
            registry=self.registry,
        )
        # info-style: value 1 under the current membership hash; replicas
        # whose hashes differ route the same session differently —
        # count(count by (hash)(...)) > 1 is the ring-divergence alert
        self.ring_membership_hash = Gauge(
            mc.ROUTER_RING_MEMBERSHIP_HASH,
            "Session-ring membership hash of this router replica "
            "(info-style gauge, value 1, labeled hash=)",
            ["hash"],
            registry=self.registry,
        )
        self._last_ring_hash: str | None = None
        self.active_streams = Gauge(
            mc.ROUTER_ACTIVE_STREAMS,
            "In-flight proxied requests (SSE streams included)",
            registry=self.registry,
        )
        self.discovery_endpoints = Gauge(
            mc.ROUTER_DISCOVERY_ENDPOINTS,
            "Endpoints service discovery currently publishes",
            registry=self.registry,
        )
        # fleet tenant accounting, re-exported from the controller's
        # /fleet/report reply (cardinality bounded by the tenant table)
        self.fleet_tenant_utilization = Gauge(
            mc.FLEET_TENANT_UTILIZATION,
            "Fleet-wide admitted request rate over the tenant's "
            "configured requests_per_s budget (1.0 = at the global limit)",
            ["tenant"],
            registry=self.registry,
        )
        self.fleet_tenant_overadmission = Gauge(
            mc.FLEET_TENANT_OVERADMISSION,
            "How far past the global per-tenant limit the N per-replica "
            "buckets over-admit (N identical replicas each granting the "
            "full budget measure about N-1)",
            ["tenant"],
            registry=self.registry,
        )
        # fleet budget scaling (docs/34-fleet-routing.md): the share of
        # each tenant's global budget this replica's buckets enforce —
        # 1/M with M live replicas, 1.0 on a single replica, scaling off,
        # or the controller-outage degradation
        self.tenant_budget_scale = Gauge(
            mc.ROUTER_TENANT_BUDGET_SCALE,
            "Share of each tenant's global budget this replica's local "
            "token buckets enforce (1.0 = full local budget)",
            registry=self.registry,
        )
        self.tenant_budget_scale.set(1.0)
        # event-loop starvation (docs/37-flight-recorder.md): decaying
        # peak of how far the lag probe's short sleep overshot its
        # deadline — a starved asyncio loop serves nothing while every
        # request-vantage metric just goes quiet
        self.event_loop_lag = Gauge(
            mc.ROUTER_EVENT_LOOP_LAG,
            "Decaying peak of asyncio event-loop scheduling lag at this "
            "replica (engine/flightrec.EventLoopLagProbe)",
            registry=self.registry,
        )
        self.event_loop_lag.set(0.0)
        # multi-tenant QoS (docs/27-multitenancy.md): the router's half of
        # the tpu:tenant_* contract — admitted traffic and per-tenant
        # throttles (429s that never reached an engine). Label cardinality
        # is bounded by the tenant table size.
        tc = lambda name, doc: Counter(  # noqa: E731
            name[: -len("_total")] if name.endswith("_total") else name,
            doc, ["tenant"], registry=self.registry,
        )
        self.tenant_requests = tc(
            mc.TENANT_REQUESTS, "Requests admitted through the QoS gate"
        )
        self.tenant_prompt_tokens = tc(
            mc.TENANT_PROMPT_TOKENS,
            "Prompt tokens metered through the QoS gate",
        )
        self.tenant_throttled = tc(
            mc.TENANT_THROTTLED,
            "Requests refused by per-tenant rate limits / concurrency caps",
        )
        self._tenant_series = {
            "requests": self.tenant_requests,
            "prompt_tokens": self.tenant_prompt_tokens,
            "throttled": self.tenant_throttled,
        }
        # per-request latency histograms (docs/28-request-tracing.md):
        # the ROUTER's vantage of the shared contract names — client-
        # visible TTFT/E2E including routing + proxy overhead (the engine
        # exports the same names plus queue/prefill/decode from its own
        # clock). Observed with trace-id exemplars at request finish.
        self.request_ttft = Histogram(
            mc.REQUEST_TTFT,
            "Request arrival at the router to first upstream byte",
            buckets=mc.REQUEST_PHASE_BUCKETS,
            registry=self.registry,
        )
        self.request_e2e = Histogram(
            mc.REQUEST_E2E,
            "Request arrival at the router to response completion",
            buckets=mc.REQUEST_PHASE_BUCKETS,
            registry=self.registry,
        )
        # inter-token latency (TPOT, docs/42-compile-telemetry.md §ITL):
        # the gap between consecutive streamed chunks as the client sees
        # them — the one client-visible SLO TTFT/E2E cannot capture.
        # Router-only: the engine's decode histogram excludes proxy +
        # network, which is exactly what this one must include.
        self.request_itl = Histogram(
            mc.REQUEST_ITL,
            "Gap between consecutive streamed chunks (client-visible "
            "inter-token latency), observed per chunk on streaming "
            "responses",
            buckets=mc.REQUEST_PHASE_BUCKETS,
            registry=self.registry,
        )

    def observe_itl(self, gap_s: float) -> None:
        """One inter-chunk gap on a streaming response."""
        self.request_itl.observe(max(0.0, gap_s))

    def observe_request(
        self,
        ttft: float | None,
        e2e: float,
        trace_id: str | None = None,
    ) -> None:
        """One served request's router-vantage latencies; the exemplar
        links a dashboard outlier straight to /debug/requests?rid=."""
        exemplar = {"trace_id": trace_id} if trace_id else None
        if ttft is not None:
            self.request_ttft.observe(max(0.0, ttft), exemplar=exemplar)
        self.request_e2e.observe(max(0.0, e2e), exemplar=exemplar)

    def _render_kv_index(self, policy) -> None:
        index = getattr(policy, "index", None)
        if index is not None:
            st = index.stats()
            self.kv_index_hashes.set(st["hashes"])
            self.kv_index_engines.set(st["engines"])
            self.kv_index_stale.set(st["stale_engines"])
            self.kv_index_events.set(st["events_applied"])
            self.kv_index_resyncs.set(st["resyncs_requested"])
            # fleet coherence: convergence-lag observations land in the
            # real histogram exactly once; per-engine seq positions are
            # re-set each scrape (clear first so gone engines drop)
            for seconds in index.convergence.drain():
                self.kv_convergence_lag.observe(seconds)
            self.kv_engine_seq.clear()
            for url, pos in index.positions().items():
                self.kv_engine_seq.labels(engine=url).set(pos["seq"])
        drain = getattr(policy, "drain_lookup_log", None)
        if drain is not None:
            for mode, seconds in drain():
                self.kv_lookups.labels(mode=mode).inc()
                self.kv_lookup_latency.labels(mode=mode).observe(seconds)
        drain_m = getattr(policy, "drain_migrate_log", None)
        if drain_m is not None:
            for decision in drain_m():
                self.kv_migrate_decisions.labels(decision=decision).inc()

    def _render_fleet(self, state) -> None:
        """Fleet-coherence gauges (docs/32-fleet-telemetry.md): ring
        membership hash, in-flight streams, discovery endpoint count, and
        the controller's fleet-view reply re-exported at this replica."""
        ring = getattr(state.policy, "ring", None)
        if ring is not None and ring.nodes():
            # empty ring (no session traffic yet) exports no hash: an idle
            # replica must not read as ring divergence
            h = ring.membership_hash()
            if h != self._last_ring_hash:
                # one series per CURRENT membership: stale hashes must not
                # linger or count(count by (hash)) sees phantom divergence
                self.ring_membership_hash.clear()
                self._last_ring_hash = h
            self.ring_membership_hash.labels(hash=h).set(1)
        elif self._last_ring_hash is not None:
            # the ring DRAINED to empty (discovery outage, scale-to-zero):
            # the old hash must stop exporting or this idle replica keeps
            # feeding phantom ring divergence against healthy ones
            self.ring_membership_hash.clear()
            self._last_ring_hash = None
        svc = getattr(state, "request_service", None)
        if svc is not None:
            self.active_streams.set(svc.active_streams)
        disc = getattr(state, "discovery", None)
        if disc is not None:
            self.discovery_endpoints.set(len(disc.endpoints()))
        reporter = getattr(state, "fleet_reporter", None)
        reply = reporter.last_reply if reporter is not None else None
        # freshness gate: during a controller outage the last reply must
        # not keep exporting as current — stale fleet gauges clear, and
        # the outage reads as absent series instead of frozen-healthy
        fresh = (
            reply is not None
            and reporter.last_report_t
            and time.monotonic() - reporter.last_report_t
            <= max(3 * reporter.interval_s, 30.0)
        )
        if fresh:
            if reply.get("divergence_blocks") is not None:
                self.kv_index_divergence.labels(
                    replica=reporter.replica_id or ""
                ).set(reply["divergence_blocks"])
            self.fleet_tenant_utilization.clear()
            self.fleet_tenant_overadmission.clear()
            for tenant, row in (reply.get("tenants") or {}).items():
                if "limit_utilization" in row:
                    self.fleet_tenant_utilization.labels(
                        tenant=tenant
                    ).set(row["limit_utilization"])
                if "overadmission_ratio" in row:
                    self.fleet_tenant_overadmission.labels(
                        tenant=tenant
                    ).set(row["overadmission_ratio"])
        elif reporter is not None:
            self.kv_index_divergence.clear()
            self.fleet_tenant_utilization.clear()
            self.fleet_tenant_overadmission.clear()

    def render(self, state, openmetrics: bool = False) -> bytes:
        self._render_kv_index(state.policy)
        self._render_fleet(state)
        probe = getattr(state, "loop_lag_probe", None)
        if probe is not None:
            self.event_loop_lag.set(probe.lag_s)
        qos = getattr(state, "qos", None)
        if qos is not None:
            self.tenant_budget_scale.set(qos.budget_scale)
            for (tenant, kind), delta in qos.drain_counter_deltas().items():
                series = self._tenant_series.get(kind)
                if series is not None:
                    series.labels(tenant=tenant).inc(delta)
        req_stats = state.request_monitor.get_request_stats()
        for url, st in req_stats.items():
            self.current_qps.labels(server=url).set(st.qps)
            self.avg_ttft.labels(server=url).set(st.ttft)
            self.avg_latency.labels(server=url).set(st.latency)
            self.in_prefill.labels(server=url).set(st.in_prefill_requests)
            self.in_decoding.labels(server=url).set(st.in_decoding_requests)
            self.finished.labels(server=url).set(st.finished_requests)
        for url, snap in state.breakers.snapshot().items():
            self.breaker_state.labels(server=url).set(snap["state_code"])
            self.breaker_opens.labels(server=url).set(snap["opens_total"])
            self.upstream_failures.labels(server=url).set(
                snap["failures_total"]
            )
        for url, st in state.engine_scraper.get_engine_stats().items():
            self.engine_running.labels(server=url).set(st.num_running_requests)
            self.engine_queuing.labels(server=url).set(st.num_queuing_requests)
            self.kv_usage.labels(server=url).set(st.hbm_kv_usage_perc)
            self.kv_hit_rate.labels(server=url).set(st.prefix_cache_hit_rate)
        self.healthy_engines.set(
            sum(
                1
                for e in state.discovery.endpoints()
                if e.healthy and not e.sleeping
            )
        )
        if openmetrics:
            from prometheus_client.openmetrics import exposition as om

            return om.generate_latest(self.registry)
        return generate_latest(self.registry)
