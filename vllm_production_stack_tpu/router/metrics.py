"""Router-side Prometheus metrics.

Per-engine gauges refreshed from the stats monitors on each /metrics scrape
(pull-time refresh instead of the reference's push-from-logger-thread,
services/metrics_service/__init__.py + routers/metrics_router.py:42-123).
Engine-scraped prefix-cache numbers are re-exported so dashboards and the
prometheus-adapter can read everything from the router.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Gauge, generate_latest

LABEL = ["server"]


class RouterMetrics:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        g = lambda name, doc: Gauge(  # noqa: E731
            name, doc, LABEL, registry=self.registry
        )
        self.current_qps = g("router_current_qps", "Arrival QPS per engine")
        self.avg_ttft = g("router_avg_ttft", "Avg time-to-first-token (s)")
        self.avg_latency = g("router_avg_latency", "Avg request latency (s)")
        self.in_prefill = g("router_num_prefill_requests", "Requests awaiting first byte")
        self.in_decoding = g("router_num_decoding_requests", "Requests streaming")
        self.finished = g("router_num_finished_requests", "Finished requests")
        self.engine_running = g(
            "router_engine_num_running_requests", "Engine-reported running requests"
        )
        self.engine_queuing = g(
            "router_engine_num_queuing_requests", "Engine-reported queued requests"
        )
        self.kv_usage = g(
            "router_engine_hbm_kv_usage_perc", "Engine-reported HBM KV usage fraction"
        )
        self.kv_hit_rate = g(
            "router_engine_prefix_cache_hit_rate", "Engine-reported prefix cache hit rate"
        )
        self.healthy_engines = Gauge(
            "router_healthy_engines_total",
            "Engines currently routable",
            registry=self.registry,
        )

    def render(self, state) -> bytes:
        req_stats = state.request_monitor.get_request_stats()
        for url, st in req_stats.items():
            self.current_qps.labels(server=url).set(st.qps)
            self.avg_ttft.labels(server=url).set(st.ttft)
            self.avg_latency.labels(server=url).set(st.latency)
            self.in_prefill.labels(server=url).set(st.in_prefill_requests)
            self.in_decoding.labels(server=url).set(st.in_decoding_requests)
            self.finished.labels(server=url).set(st.finished_requests)
        for url, st in state.engine_scraper.get_engine_stats().items():
            self.engine_running.labels(server=url).set(st.num_running_requests)
            self.engine_queuing.labels(server=url).set(st.num_queuing_requests)
            self.kv_usage.labels(server=url).set(st.hbm_kv_usage_perc)
            self.kv_hit_rate.labels(server=url).set(st.prefix_cache_hit_rate)
        self.healthy_engines.set(
            sum(
                1
                for e in state.discovery.endpoints()
                if e.healthy and not e.sleeping
            )
        )
        return generate_latest(self.registry)
