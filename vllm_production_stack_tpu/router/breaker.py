"""Per-endpoint circuit breakers for the router's upstream picks.

Before this module, `_with_failover` re-discovered a dead engine on every
request: the policy kept picking it, the connect failed, the failover loop
evicted it for THAT request only, and the next request started over. Health
probes eventually drop the pod, but a flapping endpoint (accepts TCP, dies
mid-request) can look healthy to probes indefinitely. The breaker is the
memory the failover loop lacked:

- **closed**   — normal; failures are counted, successes reset the count.
- **open**     — `failure_threshold` CONSECUTIVE failures tripped it; the
  endpoint is excluded from policy candidate sets for `cooldown_s`, which
  doubles on every re-open up to `max_cooldown_s` (exponential backoff for
  endpoints that flap right back down).
- **half_open** — cooldown expired: exactly ONE live request is let through
  as the probe (`on_attempt` reserves the slot when the pick actually goes
  to that endpoint — filtering alone must not consume it). Success closes
  the breaker; failure re-opens it with the doubled cooldown. Concurrent
  requests during the probe stay excluded; a probe that never reports back
  (wedged upstream, client vanished) frees the slot after `probe_ttl_s`.

Everything is synchronous and lock-free (single event loop); time comes
from `time.monotonic` via an injectable clock so tests drive state
transitions deterministically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..utils.logging import init_logger

logger = init_logger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# numeric encoding for the tpu:router_breaker_state gauge
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


@dataclass
class _Breaker:
    state: str = CLOSED
    consecutive_failures: int = 0
    # next cooldown to apply when (re)opening; doubles per re-open
    current_cooldown_s: float = 0.0
    open_until: float = 0.0
    probe_inflight: bool = False
    probe_started: float = 0.0
    opens_total: int = 0
    failures_total: int = 0


@dataclass
class BreakerBoard:
    """All endpoints' breakers. `failure_threshold=0` disables the board
    (allow() is always True and nothing is recorded)."""

    failure_threshold: int = 5
    cooldown_s: float = 5.0
    max_cooldown_s: float = 120.0
    probe_ttl_s: float = 30.0
    clock: callable = time.monotonic
    _breakers: dict[str, _Breaker] = field(default_factory=dict)

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def _get(self, url: str) -> _Breaker:
        b = self._breakers.get(url)
        if b is None:
            b = self._breakers[url] = _Breaker(
                current_cooldown_s=self.cooldown_s
            )
        return b

    def allow(self, url: str) -> bool:
        """May `url` receive a request right now? Pure check — nothing is
        reserved (candidate filtering runs this over every endpoint; the
        policy may pick another one). Transitions open → half_open when the
        cooldown has expired."""
        if not self.enabled:
            return True
        b = self._breakers.get(url)
        if b is None or b.state == CLOSED:
            return True
        now = self.clock()
        if b.state == OPEN:
            if now < b.open_until:
                return False
            b.state = HALF_OPEN
            b.probe_inflight = False
            logger.info("breaker for %s half-open (probing)", url)
        # half_open: one probe at a time, with a TTL so a probe whose
        # outcome never reports back can't wedge the endpoint out forever
        if b.probe_inflight and now - b.probe_started < self.probe_ttl_s:
            return False
        return True

    def on_attempt(self, url: str) -> None:
        """The failover loop picked `url` and is about to send the request:
        reserve the half-open probe slot (no-op in closed/open)."""
        if not self.enabled:
            return
        b = self._breakers.get(url)
        if b is not None and b.state == HALF_OPEN:
            b.probe_inflight = True
            b.probe_started = self.clock()

    def on_success(self, url: str) -> None:
        if not self.enabled:
            return
        b = self._breakers.get(url)
        if b is None:
            return
        if b.state != CLOSED:
            logger.info("breaker for %s closed (probe succeeded)", url)
        b.state = CLOSED
        b.consecutive_failures = 0
        b.probe_inflight = False
        b.current_cooldown_s = self.cooldown_s  # backoff resets on recovery

    def on_failure(self, url: str) -> None:
        if not self.enabled:
            return
        b = self._get(url)
        b.failures_total += 1
        b.consecutive_failures += 1
        if b.state == HALF_OPEN:
            # failed probe: straight back to open with doubled backoff
            self._open(url, b)
            return
        if b.state == CLOSED and b.consecutive_failures >= self.failure_threshold:
            self._open(url, b)

    def _open(self, url: str, b: _Breaker) -> None:
        b.state = OPEN
        b.probe_inflight = False
        b.opens_total += 1
        b.open_until = self.clock() + b.current_cooldown_s
        logger.warning(
            "breaker for %s OPEN after %d consecutive failures "
            "(cooldown %.1fs)", url, b.consecutive_failures,
            b.current_cooldown_s,
        )
        b.current_cooldown_s = min(
            self.max_cooldown_s, b.current_cooldown_s * 2
        )

    def state(self, url: str) -> str:
        b = self._breakers.get(url)
        return b.state if b is not None else CLOSED

    def prune(self, live_urls: set[str]) -> None:
        """Drop breakers for endpoints discovery no longer knows — state
        for a deleted pod's URL must not leak forever."""
        for url in list(self._breakers):
            if url not in live_urls:
                del self._breakers[url]

    def snapshot(self) -> dict[str, dict]:
        """Per-endpoint view for /metrics and debugging."""
        out = {}
        for url, b in self._breakers.items():
            out[url] = {
                "state": b.state,
                "state_code": STATE_CODES[b.state],
                "consecutive_failures": b.consecutive_failures,
                "opens_total": b.opens_total,
                "failures_total": b.failures_total,
                "open_until": b.open_until,
            }
        return out
