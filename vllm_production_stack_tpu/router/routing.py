"""Routing policies.

Five algorithms behind one interface, mirroring the reference's capability
set (routers/routing_logic.py:50-527): round-robin, session-sticky
(consistent-hash ring on a header key, QPS-min fallback), prefix-aware
(chunk-hash trie), KV-aware (asks the KV controller which engine holds the
longest cached prefix), and disaggregated-prefill (label-partitioned
prefill/decode pools). Policies are plain objects constructed by
`make_policy` and owned by the app state — reconfiguration swaps the object.

Every policy implements `async route(ctx) -> url`. Async because the
prefix/kv policies await a trie lock or a controller HTTP call; the cheap
policies just return.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

import aiohttp

from ..utils.logging import init_logger
from .discovery import Endpoint
from .engine_stats import EngineStats
from .hashring import HashRing
from .hashtrie import HashTrie
from .request_stats import RequestStats

logger = init_logger(__name__)

ROUTING_POLICIES = (
    "roundrobin",
    "session",
    "prefixaware",
    "kvaware",
    "disaggregated_prefill",
)


@dataclass
class RoutingContext:
    """Everything a policy may look at for one request."""

    endpoints: list[Endpoint]
    engine_stats: dict[str, EngineStats] = field(default_factory=dict)
    request_stats: dict[str, RequestStats] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: dict = field(default_factory=dict)

    def header(self, name: str) -> str | None:
        """Case-insensitive header lookup. HTTP header names are
        case-insensitive on the wire, and real clients vary the casing
        (urllib capitalizes: X-User-Id) — an exact dict get would silently
        break session stickiness for them."""
        val = self.headers.get(name)
        if val is not None:
            return val
        lname = name.lower()
        for k, v in self.headers.items():
            if k.lower() == lname:
                return v
        return None

    def prompt_text(self) -> str:
        """Routable text of the request: the completions prompt, or the chat
        messages' text parts joined (incl. multimodal text segments) — the
        reference's extraction (routing_logic.py:383-412)."""
        if "messages" in self.body:
            parts = []
            for msg in self.body.get("messages", []):
                content = msg.get("content", "")
                if isinstance(content, list):
                    parts.append(
                        " ".join(
                            p.get("text", "")
                            for p in content
                            if isinstance(p, dict) and p.get("type") == "text"
                        )
                    )
                elif content:
                    parts.append(str(content))
            return "\n".join(parts)
        prompt = self.body.get("prompt", "")
        if isinstance(prompt, list):
            return "\n".join(str(p) for p in prompt)
        return str(prompt)


def qps_min_url(
    endpoints: list[Endpoint], request_stats: dict[str, RequestStats]
) -> str:
    """Least-loaded fallback: an engine with no recorded requests wins
    immediately, else lowest QPS (reference _qps_routing,
    routing_logic.py:60-82)."""
    best, best_qps = None, float("inf")
    for ep in endpoints:
        st = request_stats.get(ep.url)
        if st is None:
            return ep.url
        if st.qps < best_qps:
            best_qps, best = st.qps, ep.url
    return best


class RoutingPolicy:
    name = "base"

    async def route(self, ctx: RoutingContext) -> str:
        raise NotImplementedError

    async def close(self) -> None:
        """Release any connections the policy holds (swap/shutdown)."""


class RoundRobinPolicy(RoutingPolicy):
    """URL-sorted round robin, stable under endpoint churn."""

    name = "roundrobin"

    def __init__(self) -> None:
        self._i = 0

    async def route(self, ctx: RoutingContext) -> str:
        eps = sorted(ctx.endpoints, key=lambda e: e.url)
        url = eps[self._i % len(eps)].url
        self._i += 1
        return url


class SessionPolicy(RoutingPolicy):
    """Consistent-hash the session header onto the ring; requests without a
    session id go to the least-loaded engine."""

    name = "session"

    def __init__(self, session_key: str):
        if not session_key:
            raise ValueError("session routing requires a session key header name")
        self.session_key = session_key
        self.ring = HashRing()

    async def route(self, ctx: RoutingContext) -> str:
        self.ring.sync([e.url for e in ctx.endpoints])
        session_id = ctx.header(self.session_key)
        if session_id is None:
            return qps_min_url(ctx.endpoints, ctx.request_stats)
        return self.ring.get_node(session_id)


class PrefixAwarePolicy(RoutingPolicy):
    """Longest-prefix match over the router's own chunk-hash trie; random
    choice among engines sharing the deepest prefix, then record the choice."""

    name = "prefixaware"

    def __init__(self) -> None:
        self.trie = HashTrie()

    async def route(self, ctx: RoutingContext) -> str:
        prompt = ctx.prompt_text()
        available = {e.url for e in ctx.endpoints}
        _, matched = await self.trie.longest_prefix_match(prompt, available)
        url = random.choice(sorted(matched))
        await self.trie.insert(prompt, url)
        return url


class KvawarePolicy(RoutingPolicy):
    """Ask the KV controller which engine holds the longest cached KV prefix
    for this prompt; below `threshold` matched tokens (or on any controller
    fault) fall back to least-loaded. The controller is the stack's LMCache-
    controller equivalent (engine/kv_controller.py) speaking clean REST, the
    deployment shape the reference's Go picker assumes
    (gateway_inference_extension/kv_aware_picker.go:90-133) rather than an
    in-process import."""

    name = "kvaware"

    def __init__(self, controller_url: str, threshold_tokens: int = 256):
        self.controller_url = controller_url.rstrip("/")
        self.threshold_tokens = threshold_tokens
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        # one long-lived session: the lookup is on the hot path, per-request
        # session+connection churn would tax latency and file descriptors
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=2)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def route(self, ctx: RoutingContext) -> str:
        available = {e.url for e in ctx.endpoints}
        try:
            async with self._sess().post(
                self.controller_url + "/lookup",
                json={"text": ctx.prompt_text()},
            ) as resp:
                data = await resp.json()
            url = data.get("url")
            if (
                url in available
                and data.get("matched_tokens", 0) >= self.threshold_tokens
            ):
                return url
        except Exception as e:
            logger.debug("kv controller lookup failed: %s", e)
        return qps_min_url(ctx.endpoints, ctx.request_stats)


class DisaggregatedPrefillPolicy(RoutingPolicy):
    """Partition engines into prefill/decode pools by model label; the proxy's
    2-phase orchestration calls this twice per request (phase passed in the
    body by the request service, matching the reference's max_tokens==1
    prefill convention, routing_logic.py:426-466)."""

    name = "disaggregated_prefill"

    def __init__(
        self, prefill_labels: list[str], decode_labels: list[str]
    ) -> None:
        self.prefill_labels = set(prefill_labels)
        self.decode_labels = set(decode_labels)

    def pools(self, endpoints: list[Endpoint]) -> tuple[list[Endpoint], list[Endpoint]]:
        prefill = [e for e in endpoints if e.model_label in self.prefill_labels]
        decode = [e for e in endpoints if e.model_label in self.decode_labels]
        return prefill, decode

    async def route(self, ctx: RoutingContext) -> str:
        prefill, decode = self.pools(ctx.endpoints)
        is_prefill = ctx.body.get("max_tokens", 0) == 1
        pool = prefill if is_prefill else decode
        if not pool:
            raise LookupError(
                f"no {'prefill' if is_prefill else 'decode'} engines available"
            )
        return qps_min_url(pool, ctx.request_stats)


def make_policy(name: str, **kw) -> RoutingPolicy:
    if name == "roundrobin":
        return RoundRobinPolicy()
    if name == "session":
        return SessionPolicy(kw.get("session_key", ""))
    if name == "prefixaware":
        return PrefixAwarePolicy()
    if name == "kvaware":
        return KvawarePolicy(
            kw.get("kv_controller_url", ""),
            kw.get("kv_aware_threshold", 256),
        )
    if name == "disaggregated_prefill":
        return DisaggregatedPrefillPolicy(
            kw.get("prefill_model_labels", []),
            kw.get("decode_model_labels", []),
        )
    raise ValueError(f"unknown routing policy: {name}")
