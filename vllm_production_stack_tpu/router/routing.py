"""Routing policies.

Five algorithms behind one interface, mirroring the reference's capability
set (routers/routing_logic.py:50-527): round-robin, session-sticky
(consistent-hash ring on a header key, QPS-min fallback), prefix-aware
(chunk-hash trie), KV-aware (asks the KV controller which engine holds the
longest cached prefix), and disaggregated-prefill (label-partitioned
prefill/decode pools). Policies are plain objects constructed by
`make_policy` and owned by the app state — reconfiguration swaps the object.

Every policy implements `async route(ctx) -> url`. Async because the
prefix/kv policies await a trie lock or a controller HTTP call; the cheap
policies just return.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

import aiohttp

from ..utils.http import LazyClientSession
from ..utils.logging import init_logger
from .discovery import Endpoint
from .engine_stats import EngineStats
from .hashring import HashRing
from .hashtrie import HashTrie
from .request_stats import RequestStats

logger = init_logger(__name__)

ROUTING_POLICIES = (
    "roundrobin",
    "session",
    "prefixaware",
    "kvaware",
    "disaggregated_prefill",
)


@dataclass
class RoutingContext:
    """Everything a policy may look at for one request."""

    endpoints: list[Endpoint]
    engine_stats: dict[str, EngineStats] = field(default_factory=dict)
    request_stats: dict[str, RequestStats] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: dict = field(default_factory=dict)
    # filled by SessionPolicy.route when the request carries a session id:
    # {"session_id", "owner", "ring_hash"} — the stickiness-audit stamp
    # the proxy forwards upstream (docs/32-fleet-telemetry.md). The FIRST
    # attempt's value is the affinity target; failover re-routes leave it
    # alone so a moved delivery is visible engine-side.
    sticky: dict | None = None
    # filled by KvawarePolicy.route under --kv-migrate-scoring priced when
    # a prefix owner was found: {"owner": <discovery url>,
    # "matched_tokens", "decision": "owner"|"migrate"}. On "migrate" the
    # proxy stamps x-kv-owner-hint upstream so the target engine's
    # hydration planner pulls the prefix from the owner instead of
    # recomputing it (docs/35-peer-kv-reuse.md).
    kv_hint: dict | None = None

    def header(self, name: str) -> str | None:
        """Case-insensitive header lookup. HTTP header names are
        case-insensitive on the wire, and real clients vary the casing
        (urllib capitalizes: X-User-Id) — an exact dict get would silently
        break session stickiness for them."""
        val = self.headers.get(name)
        if val is not None:
            return val
        lname = name.lower()
        for k, v in self.headers.items():
            if k.lower() == lname:
                return v
        return None

    def prompt_text(self) -> str:
        """Routable text of the request: the completions prompt, or the chat
        messages' text parts joined (incl. multimodal text segments) — the
        reference's extraction (routing_logic.py:383-412)."""
        if "messages" in self.body:
            parts = []
            for msg in self.body.get("messages", []):
                content = msg.get("content", "")
                if isinstance(content, list):
                    parts.append(
                        " ".join(
                            p.get("text", "")
                            for p in content
                            if isinstance(p, dict) and p.get("type") == "text"
                        )
                    )
                elif content:
                    parts.append(str(content))
            return "\n".join(parts)
        prompt = self.body.get("prompt", "")
        if isinstance(prompt, list):
            return "\n".join(str(p) for p in prompt)
        return str(prompt)


def qps_min_url(
    endpoints: list[Endpoint], request_stats: dict[str, RequestStats]
) -> str:
    """Least-loaded fallback: lowest live in-flight count first, then
    lowest QPS (reference _qps_routing, routing_logic.py:60-82). In-flight
    is the instant signal — windowed QPS lags, and the old "an engine with
    no recorded requests wins immediately" rule herded every concurrent
    client onto whichever engine sat idle long enough for its stats entry
    to expire, saturating engines one at a time while the rest idled. An
    unknown engine now just sorts as (0 in-flight, 0 qps): still the most
    attractive candidate, no longer an unconditional claim. Raises
    LookupError on an empty candidate list (the request service maps it to
    a clean 503) — returning None here used to surface as an
    AttributeError deep inside the proxy."""
    if not endpoints:
        raise LookupError("no engines available")

    def load(ep: Endpoint) -> tuple[int, float]:
        st = request_stats.get(ep.url)
        if st is None:
            return (0, 0.0)
        return (st.in_prefill_requests + st.in_decoding_requests, st.qps)

    return min(endpoints, key=load).url


class RoutingPolicy:
    name = "base"

    async def route(self, ctx: RoutingContext) -> str:
        raise NotImplementedError

    def on_endpoints_changed(
        self, removed: set[str], current: set[str]
    ) -> None:
        """Discovery churn hook (router/app.py wires it): policies holding
        per-endpoint state drop dead engines here instead of leaking them
        forever. Sync and non-blocking — called from discovery's publish
        path; schedule async cleanup on the running loop if needed."""

    async def close(self) -> None:
        """Release any connections the policy holds (swap/shutdown)."""


class RoundRobinPolicy(RoutingPolicy):
    """URL-sorted round robin, stable under endpoint churn."""

    name = "roundrobin"

    def __init__(self) -> None:
        self._i = 0

    async def route(self, ctx: RoutingContext) -> str:
        eps = sorted(ctx.endpoints, key=lambda e: e.url)
        if not eps:  # ZeroDivisionError from `% 0` was an opaque 500
            raise LookupError("no engines available")
        url = eps[self._i % len(eps)].url
        self._i += 1
        return url


class SessionPolicy(RoutingPolicy):
    """Consistent-hash the session header onto the ring; requests without a
    session id go to the least-loaded engine."""

    name = "session"

    def __init__(self, session_key: str):
        if not session_key:
            raise ValueError("session routing requires a session key header name")
        self.session_key = session_key
        self.ring = HashRing()

    async def route(self, ctx: RoutingContext) -> str:
        if not ctx.endpoints:  # get_node on an empty ring returns None
            raise LookupError("no engines available")
        self.ring.sync([e.url for e in ctx.endpoints])
        session_id = ctx.header(self.session_key)
        if session_id is None:
            return qps_min_url(ctx.endpoints, ctx.request_stats)
        owner = self.ring.get_node(session_id)
        # stickiness-audit stamp (docs/32-fleet-telemetry.md): the ring-
        # chosen owner + this ring's membership hash ride upstream so the
        # engine can detect affinity breaks (owner changed between
        # requests, or delivery moved off the owner via failover)
        ctx.sticky = {
            "session_id": session_id,
            "owner": owner,
            "ring_hash": self.ring.membership_hash(),
        }
        return owner

    def on_endpoints_changed(
        self, removed: set[str], current: set[str]
    ) -> None:
        # route() re-syncs per request anyway; syncing on churn too means a
        # dead engine leaves the ring even on an idle router
        self.ring.sync(sorted(current))


class PrefixAwarePolicy(RoutingPolicy):
    """Longest-prefix match over the router's own chunk-hash trie; random
    choice among engines sharing the deepest prefix, then record the choice."""

    name = "prefixaware"

    # how long a disappeared endpoint keeps its trie slice: route() already
    # filters candidates by the live endpoint set, so the scrub is purely a
    # memory reclaim for truly-gone engines — firing it on the first missed
    # health probe would erase a flapping engine's prefix affinity and
    # collapse its cache hit rate until re-learned
    scrub_grace_s: float = 120.0

    def __init__(self) -> None:
        self.trie = HashTrie()
        # url -> pending delayed-scrub task (strong refs: the loop holds
        # only weak task references, so a dropped handle could be GC'd
        # mid-scrub and leave the dead endpoint in the trie after all)
        self._scrubs: dict[str, asyncio.Task] = {}

    async def route(self, ctx: RoutingContext) -> str:
        prompt = ctx.prompt_text()
        available = {e.url for e in ctx.endpoints}
        if not available:
            raise LookupError("no engines available")
        _, matched = await self.trie.longest_prefix_match(prompt, available)
        url = random.choice(sorted(matched))
        await self.trie.insert(prompt, url)
        return url

    def on_endpoints_changed(
        self, removed: set[str], current: set[str]
    ) -> None:
        # scrub dead engines from the trie — without this remove_endpoint
        # was dead code and a drained pod's memory stayed pinned under
        # every prefix it ever served. Scrubs run after scrub_grace_s so a
        # health-probe flap cancels them on the way back up.
        for url in current:
            task = self._scrubs.pop(url, None)
            if task is not None:
                task.cancel()
        if not removed:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # constructor-time publish; nothing to scrub yet
        for url in removed:
            if url in self._scrubs:
                continue
            task = loop.create_task(self._delayed_scrub(url))
            self._scrubs[url] = task
            task.add_done_callback(
                lambda t, url=url: (
                    self._scrubs.pop(url, None)
                    if self._scrubs.get(url) is t else None
                )
            )

    async def _delayed_scrub(self, url: str) -> None:
        await asyncio.sleep(self.scrub_grace_s)
        await self.trie.remove_endpoint(url)


class KvawarePolicy(RoutingPolicy):
    """Route to the engine holding the longest cached KV prefix for this
    prompt; below `threshold` matched tokens (or when nothing can answer)
    fall back to least-loaded.

    Two lookup modes:

    - **embedded** (index is not None): the router hosts the cluster KV
      index in-process (kv_index.ClusterKVIndex; router/app.py mounts
      /kv/events so engines publish straight to the router) — the lookup is
      a tokenize + chain-hash + set walk with ZERO network hops on the
      request path. Engines not publishing (or stale after a sequence gap)
      make the index non-authoritative for them; the policy then falls back
      to the controller hop when one is configured, else least-loaded.
    - **controller** (index is None): the original two-hop shape — ask the
      REST KV controller (engine/kv_controller.py), which itself answers
      from ITS index or fans out to legacy engines
      (gateway_inference_extension/kv_aware_picker.go:90-133 parity).
    """

    name = "kvaware"

    # floor of the per-extra-queued-request wait estimate used when an
    # engine has no measured TTFT yet (fresh fleet) — seconds of queueing
    # one more in-flight request costs at a busy engine
    SEAT_COST_S = 0.05
    # exploration rule that breaks the measurement circularity: the
    # target's peer bandwidth is only ever measured BY a peer pull, and a
    # pull only happens after a migrate hint — so a fleet that never
    # migrates never prices. When the owner is ahead of the target by at
    # least this many requests, migrate even with the link unmeasured:
    # worst case the (idle) target recomputes, which already beats
    # queueing that deep at a drowning owner, and the pull that does
    # happen is what crosses the bandwidth sample floor.
    UNPRICED_MIGRATE_EXCESS = 8.0

    def __init__(self, controller_url: str = "", threshold_tokens: int = 256,
                 index=None, tokenizer=None, migrate_scoring: str = "off"):
        self.controller_url = (controller_url or "").rstrip("/")
        self.threshold_tokens = threshold_tokens
        # embedded mode: a kv_index.ClusterKVIndex + something with
        # .encode(text) -> token ids (the shared engine tokenizer)
        self.index = index
        self.tokenizer = tokenizer
        # priced route-vs-migrate (docs/35-peer-kv-reuse.md): "off" always
        # follows the prefix owner (the historical behavior); "priced"
        # scores route-to-owner vs route-to-least-loaded + peer-pull from
        # the owner's load/TTFT and the target's fleet-reported peer
        # bandwidth, stamping x-kv-owner-hint upstream on migrate
        if migrate_scoring not in ("off", "priced"):
            raise ValueError(
                f"kv_migrate_scoring {migrate_scoring!r}; "
                "expected 'off' or 'priced'"
            )
        self.migrate_scoring = migrate_scoring
        self._http = LazyClientSession(timeout=aiohttp.ClientTimeout(total=2))
        # (mode, seconds) lookup observations, drained by RouterMetrics
        self._lookup_log: list[tuple[str, float]] = []
        # migrate decisions ("owner"|"migrate"), drained by RouterMetrics
        # into tpu:router_kv_migrate_decisions_total
        self._migrate_log: list[str] = []
        # rate limiter for the publish-url/discovery-url mismatch warning
        self._mismatch_warn_t = 0.0

    async def _sess(self) -> aiohttp.ClientSession:
        return await self._http.get()

    async def close(self) -> None:
        await self._http.close()

    # NOTE deliberately no on_endpoints_changed: freeing an index slice on
    # discovery churn would turn every health-probe flap into a full
    # snapshot resync. Lookups already restrict to currently-available
    # endpoints, the liveness TTL drops dead publishers from answers, and
    # ClusterKVIndex purges truly-gone engines' memory after a long grace;
    # explicit /deregister (router/app.py) still frees a slice immediately.

    def drain_lookup_log(self) -> list[tuple[str, float]]:
        log, self._lookup_log = self._lookup_log, []
        return log

    def drain_migrate_log(self) -> list[str]:
        log, self._migrate_log = self._migrate_log, []
        return log

    def _observe(self, mode: str, seconds: float) -> None:
        self._lookup_log.append((mode, seconds))
        if len(self._lookup_log) > 10000:  # scrape stopped; stay bounded
            del self._lookup_log[:5000]

    async def _indexed_lookup(self, ctx, available):
        """(url, matched_tokens, authoritative, elapsed_s): authoritative
        only when EVERY available engine has a fresh index slice — a partial
        cluster view must escalate to the controller hop (which fans out to
        the legacy/stale engines) instead of silently degrading to
        least-loaded for engines the index can't speak for. elapsed_s is
        None when the index couldn't attempt the lookup at all (route()
        observes each request under exactly one mode)."""
        fresh = self.index.fresh_engines(available)
        if not fresh:
            all_fresh = self.index.fresh_engines()
            now = time.monotonic()
            if all_fresh and now - self._mismatch_warn_t > 60.0:
                # engines ARE publishing, just under URLs discovery doesn't
                # know (POD_IP:ENGINE_PORT vs a service DNS name) — without
                # this warning the index silently never answers anything
                self._mismatch_warn_t = now
                logger.warning(
                    "embedded KV index has fresh publishers %s but none "
                    "match discovery endpoints %s — check POD_IP/"
                    "ENGINE_PORT vs the discovery URL scheme; indexed "
                    "routing is disabled until they agree",
                    sorted(all_fresh), sorted(available),
                )
            return None, 0, False, None
        loop = asyncio.get_running_loop()
        t0 = time.perf_counter()
        # tokenize off-loop: multi-KB chat prompts would stall the router
        ids = await loop.run_in_executor(
            None, self.tokenizer.encode, ctx.prompt_text()
        )
        url, matched = self.index.lookup_token_ids(ids, available)
        elapsed = time.perf_counter() - t0
        # route() pre-normalizes, so set equality is exact
        return url, matched, fresh == available, elapsed

    def _resolve_owner(
        self, ctx: RoutingContext, owner_url: str, matched: int
    ) -> str:
        """Final pick once a prefix owner with `matched` cached tokens is
        known — the priced route-vs-migrate policy
        (docs/35-peer-kv-reuse.md). "off" keeps the historical
        follow-the-owner behavior. "priced" compares, in seconds:

        - **route-to-owner**: the owner's measured avg TTFT (its queue
          wait under current load), floored by a per-excess-request
          heuristic (SEAT_COST_S) while TTFT is still unmeasured;
        - **route-to-least-loaded + peer-pull**: the least-loaded
          engine's measured TTFT plus the migration cost
          ``matched_tokens × kv_bytes_per_token ÷ wire_bandwidth`` from
          the target's scraped tpu:kv_bytes_per_token and the faster of
          its measured tpu:kv_tier_bandwidth_bytes_per_s
          {tier="peer"|"device",direction="in"} links.

        Migration requires a strictly-less-loaded target and, normally, a
        measured peer bandwidth (>0) — the router-side analogue of the
        engine planner's sample-floor rule. The one exception is the
        exploration rule (UNPRICED_MIGRATE_EXCESS): an owner ahead of
        the target by that many requests migrates even unmeasured,
        because the pull it triggers is the only thing that can ever
        measure the link (and an idle target recomputing already beats
        queueing that deep). On migrate the owner hint rides upstream
        (ctx.kv_hint → x-kv-owner-hint) so the target's hydration
        planner skips cluster rediscovery."""
        if self.migrate_scoring != "priced":
            return owner_url
        decision = "owner"
        pick = owner_url
        stats = ctx.engine_stats
        rstats = ctx.request_stats

        def load(u: str) -> float:
            st = stats.get(u)
            return st.load if st is not None else 0.0

        def ttft(u: str) -> float:
            st = rstats.get(u)
            return st.ttft if st is not None else 0.0

        others = [e.url for e in ctx.endpoints if e.url != owner_url]
        if others:
            target = min(others, key=lambda u: (load(u), u))
            tstat = stats.get(target)
            owner_load, target_load = load(owner_url), load(target)
            # fastest measured wire into the target wins: HTTP peer pulls
            # vs device-path collectives (docs/39-device-peer-kv.md) — a
            # measured device link reprices migration without any config
            peer_bw = max(
                tstat.kv_peer_bw_in_bytes_per_s if tstat is not None else 0.0,
                (
                    tstat.kv_device_bw_in_bytes_per_s
                    if tstat is not None
                    else 0.0
                ),
            )
            bpt = tstat.kv_bytes_per_token if tstat is not None else 0.0
            if bpt <= 0.0:
                ostat = stats.get(owner_url)
                bpt = ostat.kv_bytes_per_token if ostat is not None else 0.0
            if target_load < owner_load and peer_bw > 0.0 and bpt > 0.0:
                migrate_s = matched * bpt / peer_bw
                owner_wait = max(
                    ttft(owner_url),
                    (owner_load - target_load) * self.SEAT_COST_S,
                )
                target_wait = ttft(target) + migrate_s
                if target_wait < owner_wait:
                    decision = "migrate"
                    pick = target
            elif (
                target_load < owner_load
                and owner_load - target_load >= self.UNPRICED_MIGRATE_EXCESS
            ):
                # unmeasured link, drowning owner: explore (see
                # UNPRICED_MIGRATE_EXCESS) — the pull this triggers is
                # what makes the NEXT decision priced
                decision = "migrate"
                pick = target
        self._migrate_log.append(decision)
        if len(self._migrate_log) > 10000:  # scrape stopped; stay bounded
            del self._migrate_log[:5000]
        ctx.kv_hint = {
            "owner": owner_url,
            "matched_tokens": matched,
            "decision": decision,
        }
        return pick

    @staticmethod
    def _adapter_model(ctx: RoutingContext) -> str | None:
        """The request's model name IF discovery knows it as a LoRA adapter
        (ModelInfo.parent set). Adapter KV chains are salted with an
        engine-local salt, so neither the embedded index nor the
        controller's index can hash them — only engine-side probes can."""
        model = ctx.body.get("model")
        if not model:
            return None
        for ep in ctx.endpoints:
            info = ep.model_info.get(model)
            if info is not None and info.parent:
                return model
        return None

    async def route(self, ctx: RoutingContext) -> str:
        # normalized -> discovery-shaped url: the index and the controller
        # both answer with rstripped urls, while discovery may carry a
        # trailing slash — membership checks and the returned url must go
        # through this map or a resident match is silently discarded
        by_norm = {e.url.rstrip("/"): e.url for e in ctx.endpoints}
        available = set(by_norm)
        adapter = self._adapter_model(ctx)
        # each request is observed under exactly ONE mode: "indexed" when
        # the embedded index settled it, "controller" for a pure controller
        # hop, "mixed" when a non-authoritative index attempt escalated to
        # the controller (sum over modes == routed KV-aware requests)
        idx_elapsed = None
        if (
            self.index is not None
            and self.tokenizer is not None
            and adapter is None
        ):
            try:
                url, matched, authoritative, idx_elapsed = (
                    await self._indexed_lookup(ctx, available)
                )
            except Exception as e:
                # a tokenizer/index fault must degrade to the fallback
                # chain, not turn every request into a 500 (the controller
                # path below has the same guard)
                logger.debug("embedded kv index lookup failed: %s", e)
                url, matched, authoritative, idx_elapsed = None, 0, False, None
            if url in by_norm and matched >= self.threshold_tokens:
                self._observe("indexed", idx_elapsed or 0.0)
                return self._resolve_owner(ctx, by_norm[url], matched)
            if authoritative:
                # the index answered for every available engine: a short
                # match is a real "nothing cached" — go least-loaded, do
                # NOT add a controller hop that would say the same thing
                self._observe("indexed", idx_elapsed or 0.0)
                return qps_min_url(ctx.endpoints, ctx.request_stats)
        if self.controller_url:
            t0 = time.perf_counter()
            payload = {"text": ctx.prompt_text()}
            if adapter is not None:
                # the controller's index can't hash salted adapter chains;
                # naming the model makes it fan out to engine-side probes
                payload["model"] = adapter
            try:
                sess = await self._sess()
                async with sess.post(
                    self.controller_url + "/lookup", json=payload
                ) as resp:
                    data = await resp.json()
                elapsed = time.perf_counter() - t0
                if idx_elapsed is not None:
                    self._observe("mixed", idx_elapsed + elapsed)
                else:
                    self._observe("controller", elapsed)
                url = (data.get("url") or "").rstrip("/")
                if (
                    url in by_norm
                    and data.get("matched_tokens", 0) >= self.threshold_tokens
                ):
                    return self._resolve_owner(
                        ctx, by_norm[url],
                        int(data.get("matched_tokens", 0)),
                    )
            except Exception as e:
                logger.debug("kv controller lookup failed: %s", e)
                # a failed hop still counts — during a controller outage the
                # lookup metrics must keep tracking routed traffic (and the
                # histogram must show the timeout-bound latencies)
                elapsed = time.perf_counter() - t0
                if idx_elapsed is not None:
                    self._observe("mixed", idx_elapsed + elapsed)
                else:
                    self._observe("controller", elapsed)
        elif idx_elapsed is not None:
            self._observe("indexed", idx_elapsed)
        return qps_min_url(ctx.endpoints, ctx.request_stats)


class DisaggregatedPrefillPolicy(RoutingPolicy):
    """Partition engines into prefill/decode pools; the proxy's 2-phase
    orchestration calls this twice per request (phase passed in the body by
    the request service, matching the reference's max_tokens==1 prefill
    convention, routing_logic.py:426-466).

    Pool membership is a RUNTIME property (docs/40-pool-rebalancing.md):
    an engine advertising a live role via tpu:pool_role (scraped into
    EngineStats.role — the rebalancer flips it through POST /role) routes
    by THAT role; engines with no scraped role fall back to the frozen
    helm model-label mapping, so the policy degrades to the static
    partition when the scraper is cold or rebalancing is off."""

    name = "disaggregated_prefill"

    def __init__(
        self, prefill_labels: list[str], decode_labels: list[str]
    ) -> None:
        self.prefill_labels = set(prefill_labels)
        self.decode_labels = set(decode_labels)

    def _role_of(
        self, e: Endpoint, engine_stats: dict[str, EngineStats] | None
    ) -> str:
        stats = (engine_stats or {}).get(e.url)
        if stats is not None and stats.role in ("prefill", "decode"):
            return stats.role
        if e.model_label in self.prefill_labels:
            return "prefill"
        if e.model_label in self.decode_labels:
            return "decode"
        return ""

    def pools(
        self,
        endpoints: list[Endpoint],
        engine_stats: dict[str, EngineStats] | None = None,
    ) -> tuple[list[Endpoint], list[Endpoint]]:
        prefill, decode = [], []
        for e in endpoints:
            role = self._role_of(e, engine_stats)
            if role == "prefill":
                prefill.append(e)
            elif role == "decode":
                decode.append(e)
        return prefill, decode

    async def route(self, ctx: RoutingContext) -> str:
        prefill, decode = self.pools(ctx.endpoints, ctx.engine_stats)
        is_prefill = ctx.body.get("max_tokens", 0) == 1
        pool = prefill if is_prefill else decode
        if not pool:
            raise LookupError(
                f"no {'prefill' if is_prefill else 'decode'} engines available"
            )
        return qps_min_url(pool, ctx.request_stats)


def make_policy(name: str, **kw) -> RoutingPolicy:
    if name == "roundrobin":
        return RoundRobinPolicy()
    if name == "session":
        return SessionPolicy(kw.get("session_key", ""))
    if name == "prefixaware":
        return PrefixAwarePolicy()
    if name == "kvaware":
        index = tokenizer = None
        if kw.get("kv_index_mode", "controller") == "embedded":
            from ..kv_index import ClusterKVIndex
            from ..utils.tokenizer import hashing_tokenizer

            spec = kw.get("kv_index_tokenizer")
            if not spec:
                # same rule args.py enforces for the CLI — dynamic-config
                # swaps come through here without that validation, and
                # silently defaulting to the byte tokenizer would hash
                # prompts differently from HF-tokenized engines: every
                # lookup matches 0 and kvaware degrades to least-loaded
                # with no sign anything is wrong
                raise ValueError(
                    "kvaware embedded index mode requires "
                    "kv_index_tokenizer (a tokenizer dir, or 'byte')"
                )
            index = ClusterKVIndex()
            tokenizer = hashing_tokenizer(spec)
        return KvawarePolicy(
            kw.get("kv_controller_url") or "",
            kw.get("kv_aware_threshold", 256),
            index=index,
            tokenizer=tokenizer,
            migrate_scoring=kw.get("kv_migrate_scoring") or "off",
        )
    if name == "disaggregated_prefill":
        return DisaggregatedPrefillPolicy(
            kw.get("prefill_model_labels", []),
            kw.get("decode_model_labels", []),
        )
    raise ValueError(f"unknown routing policy: {name}")
