"""Router-side per-engine request statistics.

The proxy calls three hooks as each request flows through it —
`on_new_request` (arrival), `on_first_token` (TTFT), `on_request_complete`
(latency) — and QPS/TTFT/latency are computed over a sliding time window,
like the reference's RequestStatsMonitor (stats/request_stats.py:58-306).
QPS counts arrivals in the window; TTFT/latency average over completions in
the window. Routing's QPS-min fallback and the /metrics endpoint read these.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass


class SlidingWindow:
    """(timestamp, value) samples; O(1) amortized expiry."""

    def __init__(self, window: float):
        self.window = window
        self._samples: deque[tuple[float, float]] = deque()
        self._sum = 0.0

    def add(self, ts: float, value: float) -> None:
        self._samples.append((ts, value))
        self._sum += value
        self._expire(ts)

    def _expire(self, now: float) -> None:
        cutoff = now - self.window
        while self._samples and self._samples[0][0] < cutoff:
            _, v = self._samples.popleft()
            self._sum -= v

    def rate(self, now: float) -> float:
        self._expire(now)
        return len(self._samples) / self.window

    def average(self, now: float) -> float:
        self._expire(now)
        return self._sum / len(self._samples) if self._samples else 0.0


@dataclass
class RequestStats:
    qps: float = 0.0
    ttft: float = 0.0  # avg seconds to first byte
    latency: float = 0.0  # avg end-to-end seconds
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uptime: float = 0.0


class RequestStatsMonitor:
    def __init__(self, sliding_window: float = 60.0):
        self.sliding_window = sliding_window
        self._qps: dict[str, SlidingWindow] = {}
        self._ttft: dict[str, SlidingWindow] = {}
        self._latency: dict[str, SlidingWindow] = {}
        self._start: dict[tuple[str, str], float] = {}
        self._first_token: dict[tuple[str, str], float] = {}
        self.in_prefill: dict[str, int] = {}
        self.in_decoding: dict[str, int] = {}
        self.finished: dict[str, int] = {}
        self.first_request_time: float | None = None

    def _win(self, table: dict, url: str) -> SlidingWindow:
        if url not in table:
            table[url] = SlidingWindow(self.sliding_window)
        return table[url]

    # -- hooks (called by the proxy) --------------------------------------

    def on_new_request(self, url: str, request_id: str, ts: float) -> None:
        self._start[(url, request_id)] = ts
        self.in_prefill[url] = self.in_prefill.get(url, 0) + 1
        self._win(self._qps, url).add(ts, 1.0)
        if self.first_request_time is None:
            self.first_request_time = ts

    def on_first_token(self, url: str, request_id: str, ts: float) -> None:
        key = (url, request_id)
        start = self._start.get(key)
        if start is None or key in self._first_token:
            return
        self._first_token[key] = ts
        self.in_prefill[url] = max(0, self.in_prefill.get(url, 1) - 1)
        self.in_decoding[url] = self.in_decoding.get(url, 0) + 1
        self._win(self._ttft, url).add(ts, ts - start)

    def on_request_complete(self, url: str, request_id: str, ts: float) -> None:
        key = (url, request_id)
        start = self._start.pop(key, None)
        if start is None:
            return
        if self._first_token.pop(key, None) is not None:
            self.in_decoding[url] = max(0, self.in_decoding.get(url, 1) - 1)
        else:
            # completed without any byte (error/abort) — still leaves prefill
            self.in_prefill[url] = max(0, self.in_prefill.get(url, 1) - 1)
        self.finished[url] = self.finished.get(url, 0) + 1
        self._win(self._latency, url).add(ts, ts - start)

    # -- snapshot ---------------------------------------------------------

    def get_request_stats(self, now: float | None = None) -> dict[str, RequestStats]:
        now = time.time() if now is None else now
        urls = (
            set(self._qps) | set(self.in_prefill) | set(self.in_decoding)
            | set(self.finished)
        )
        out = {}
        for url in urls:
            out[url] = RequestStats(
                qps=self._win(self._qps, url).rate(now),
                ttft=self._win(self._ttft, url).average(now),
                latency=self._win(self._latency, url).average(now),
                in_prefill_requests=self.in_prefill.get(url, 0),
                in_decoding_requests=self.in_decoding.get(url, 0),
                finished_requests=self.finished.get(url, 0),
                uptime=(
                    now - self.first_request_time
                    if self.first_request_time
                    else 0.0
                ),
            )
        return out
