"""Pluggable request-body rewriting before proxying.

Reference: services/request_service/rewriter.py:29-70 — an interface with a
no-op default; operators subclass to mutate bodies (inject defaults, strip
fields) without touching the proxy."""

from __future__ import annotations


class RequestRewriter:
    def rewrite(self, path: str, body: dict) -> dict:
        raise NotImplementedError


class NoopRequestRewriter(RequestRewriter):
    def rewrite(self, path: str, body: dict) -> dict:
        return body


def make_rewriter(spec: str | None) -> RequestRewriter:
    """`spec` is "module:ClassName" importable from PYTHONPATH, or None."""
    if not spec:
        return NoopRequestRewriter()
    import importlib

    mod_name, _, cls_name = spec.partition(":")
    cls = getattr(importlib.import_module(mod_name), cls_name or "Rewriter")
    return cls()
