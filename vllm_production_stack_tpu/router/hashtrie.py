"""Prefix hash-trie for prefix-aware routing.

Prompts are split into fixed-size character chunks; each chunk is xxhash64'd
and the hash sequence walks a trie whose nodes record which endpoints have
seen that prefix (reference prefix/hashtrie.py:24-103, chunk size 128 —
matching the Go gateway picker, prefix_aware_picker.go:134-213). The router
inserts the prompt under whichever endpoint it picked, so the trie converges
to "who has which prefix cached" without talking to the engines.

Mutations and lookups take one asyncio lock: trie ops are microseconds of
pure CPU, so per-node locks (the reference's choice) buy contention relief
the router doesn't need.
"""

from __future__ import annotations

import asyncio

import xxhash

CHUNK_CHARS = 128


class _Node:
    __slots__ = ("children", "endpoints")

    def __init__(self) -> None:
        self.children: dict[int, _Node] = {}
        self.endpoints: set[str] = set()


class HashTrie:
    def __init__(self, chunk_chars: int = CHUNK_CHARS):
        self.chunk_chars = chunk_chars
        self.root = _Node()
        self._lock = asyncio.Lock()

    def _chunks(self, text: str):
        for i in range(0, len(text), self.chunk_chars):
            yield xxhash.xxh64_intdigest(text[i : i + self.chunk_chars])

    async def insert(self, text: str, endpoint: str) -> None:
        # hash BEFORE taking the lock: xxhashing a multi-KB prompt is the
        # expensive part, and doing it under the lock serialized every other
        # routing decision behind this one
        hashes = list(self._chunks(text))
        async with self._lock:
            node = self.root
            node.endpoints.add(endpoint)
            for h in hashes:
                node = node.children.setdefault(h, _Node())
                node.endpoints.add(endpoint)

    async def longest_prefix_match(
        self, text: str, available: set[str] | None = None
    ) -> tuple[int, set[str]]:
        """Returns (matched chunk count, endpoints sharing that prefix). When
        nothing matches, the candidate set falls back to `available` (pick
        anywhere, then insert) — reference hashtrie.py:76-103."""
        hashes = list(self._chunks(text))  # hash outside the lock (insert too)
        async with self._lock:
            node = self.root
            matched = 0
            best: set[str] = set()
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    break
                cand = (
                    nxt.endpoints & available
                    if available is not None
                    else nxt.endpoints
                )
                if not cand:
                    break
                node = nxt
                matched += 1
                best = cand
        if not best:
            best = set(available) if available else set()
        return matched, best

    async def remove_endpoint(self, endpoint: str) -> None:
        """Drop a dead endpoint everywhere (lazily pruning empty nodes is not
        worth the bookkeeping at router scale)."""
        async with self._lock:
            stack = [self.root]
            while stack:
                node = stack.pop()
                node.endpoints.discard(endpoint)
                stack.extend(node.children.values())
