"""Router-side fleet coherence reporting (docs/32-fleet-telemetry.md).

`FleetReporter` is a background task that periodically POSTs this
replica's coherence state to the fleet aggregation endpoint (the KV
controller's /fleet/report): ring-membership hash, embedded KV-index
positions, breaker states, and the per-tenant drained counters the
controller rolls up into fleet-wide tenant accounting.

The reply rides back fleet-level signals this replica cannot compute
alone — its index divergence against the controller's authoritative
index, fleet tenant utilization/over-admission, and the ring-divergence
flag — and RouterMetrics re-exports them, so the fleet view is scrapeable
at every replica without adding a scrape target.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp

from ..utils.http import LazyClientSession
from ..utils.logging import init_logger

logger = init_logger(__name__)


class FleetReporter:
    def __init__(self, state, url: str, interval_s: float = 10.0,
                 replica_id: str = ""):
        self.state = state  # RouterState (app.py)
        self.url = url.rstrip("/")
        self.interval_s = interval_s
        self.replica_id = replica_id
        self._http = LazyClientSession(
            timeout=aiohttp.ClientTimeout(total=max(2.0, interval_s))
        )
        self._task: asyncio.Task | None = None
        # last successful reply (divergence, fleet tenants, ring flag) —
        # read by RouterMetrics.render and /debug/fleet
        self.last_reply: dict | None = None
        self.last_report_t: float = 0.0
        self.last_error: str | None = None
        self.reports_sent = 0
        self.report_failures = 0

    async def start(self) -> None:
        if self.interval_s > 0 and self.url:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self._http.close()

    async def _run(self) -> None:
        while True:
            try:
                await self.report_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep reporting through faults
                self.report_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                logger.debug("fleet report failed: %s", e)
            await asyncio.sleep(self.interval_s)

    def build_report(self) -> dict:
        """This replica's coherence state, as one JSON-able dict."""
        state = self.state
        report: dict = {
            "replica": self.replica_id,
            "ts": time.time(),
            "ring_hash": "",
            "breakers": {},
            "tenants": {},
        }
        ring = getattr(state.policy, "ring", None)
        if ring is not None and ring.nodes():
            # an EMPTY ring (no session traffic routed yet) reports no
            # hash: an idle replica must not trip the ring-divergence
            # alert against busy ones
            report["ring_hash"] = ring.membership_hash()
        index = getattr(state.policy, "index", None)
        if index is not None:
            report["index"] = index.positions()
        try:
            report["breakers"] = {
                url: snap["state_code"]
                for url, snap in state.breakers.snapshot().items()
            }
        except Exception:  # breakers are optional context, never fatal
            pass
        qos = getattr(state, "qos", None)
        if qos is not None:
            report["tenants"] = qos.totals()
        return report

    async def report_once(self) -> dict:
        """One report round; returns (and stores) the controller reply."""
        sess = await self._http.get()
        async with sess.post(
            self.url + "/fleet/report", json=self.build_report()
        ) as resp:
            reply = await resp.json()
            if resp.status != 200:
                raise RuntimeError(
                    f"fleet endpoint returned HTTP {resp.status}: {reply}"
                )
        self.reports_sent += 1
        self.last_reply = reply
        self.last_report_t = time.monotonic()
        self.last_error = None
        return reply

    def snapshot(self) -> dict:
        """/debug/fleet view of the reporting loop itself."""
        return {
            "url": self.url,
            "interval_s": self.interval_s,
            "reports_sent": self.reports_sent,
            "report_failures": self.report_failures,
            "last_error": self.last_error,
            "last_report_age_s": (
                round(time.monotonic() - self.last_report_t, 3)
                if self.last_report_t else None
            ),
            "last_reply": self.last_reply,
        }


def debug_fleet_snapshot(state) -> dict:
    """The router's GET /debug/fleet body: this replica's own coherence
    state (ring membership, embedded index positions, breakers, stickiness
    stamps it emits) plus the last fleet-view reply if reporting is on."""
    policy = state.policy
    ring = getattr(policy, "ring", None)
    index = getattr(policy, "index", None)
    body: dict = {
        "replica": getattr(state.args, "router_replica_id", None),
        "policy": type(policy).__name__,
        "ring_hash": ring.membership_hash() if ring is not None else None,
        "ring_nodes": sorted(ring.nodes()) if ring is not None else None,
        "index": index.positions() if index is not None else None,
        "index_convergence": (
            index.convergence.stats() if index is not None else None
        ),
        "breakers": state.breakers.snapshot(),
        "endpoints": [e.url for e in state.discovery.endpoints()],
        "active_streams": state.request_service.active_streams,
        "fleet_report": (
            state.fleet_reporter.snapshot()
            if getattr(state, "fleet_reporter", None) is not None
            else None
        ),
    }
    return body
