"""Router-side fleet coherence reporting (docs/32-fleet-telemetry.md).

`FleetReporter` is a background task that periodically POSTs this
replica's coherence state to the fleet aggregation endpoint (the KV
controller's /fleet/report): ring-membership hash, embedded KV-index
positions, breaker states, and the per-tenant drained counters the
controller rolls up into fleet-wide tenant accounting.

The reply rides back fleet-level signals this replica cannot compute
alone — its index divergence against the controller's authoritative
index, fleet tenant utilization/over-admission, the ring-divergence
flag, and the live replica count — and RouterMetrics re-exports them, so
the fleet view is scrapeable at every replica without adding a scrape
target. The replica count also CLOSES the tenant-budget loop
(docs/34-fleet-routing.md): with budget scaling on, the reporter re-rates
this replica's local token buckets to a 1/M share of each tenant's
global budget, and degrades back to the full local budget when the
controller goes silent — no synchronous hop ever lands on the admission
path.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp

from ..utils.http import LazyClientSession
from ..utils.logging import init_logger
from ..utils.system import jittered_interval

logger = init_logger(__name__)

# ±fraction of the report interval each sleep is jittered by: M replicas
# rolling out together must de-correlate instead of POSTing /fleet/report
# on synchronized ticks (the same thundering-herd guard the KV event
# publisher applies toward its subscribers)
DEFAULT_JITTER_FRAC = 0.15


class FleetReporter:
    def __init__(self, state, url: str, interval_s: float = 10.0,
                 replica_id: str = "", budget_scaling: bool = True,
                 jitter_frac: float = DEFAULT_JITTER_FRAC):
        self.state = state  # RouterState (app.py)
        self.url = url.rstrip("/")
        self.interval_s = interval_s
        self.replica_id = replica_id
        # closes the tenant-budget loop from the reply's replica count;
        # off = report-only (the PR 9 measurement behavior)
        self.budget_scaling = budget_scaling
        self.jitter_frac = jitter_frac
        self._http = LazyClientSession(
            timeout=aiohttp.ClientTimeout(total=max(2.0, interval_s))
        )
        self._task: asyncio.Task | None = None
        # last successful reply (divergence, fleet tenants, ring flag) —
        # read by RouterMetrics.render and /debug/fleet
        self.last_reply: dict | None = None
        self.last_report_t: float = 0.0
        self.last_error: str | None = None
        self.reports_sent = 0
        self.report_failures = 0

    async def start(self) -> None:
        if self.interval_s > 0 and self.url:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self._http.close()

    def _next_interval(self) -> float:
        """The next sleep, jittered so replicas never POST /fleet/report
        on synchronized ticks (utils.system.jittered_interval is the one
        shared herd-avoidance policy)."""
        return jittered_interval(self.interval_s, self.jitter_frac)

    async def _run(self) -> None:
        while True:
            try:
                await self.report_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep reporting through faults
                self.report_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                logger.debug("fleet report failed: %s", e)
                self._degrade_if_stale()
            await asyncio.sleep(self._next_interval())

    def _degrade_if_stale(self) -> None:
        """Controller-outage degradation: once the last successful report
        is older than 3 report intervals (the same freshness rule the
        metrics re-export uses), scaled budgets fall back to the FULL
        local budget — a dead controller must cost budget coherence, never
        availability. Re-tightens automatically on the next successful
        report."""
        if not self.budget_scaling:
            return
        qos = getattr(self.state, "qos", None)
        if qos is None:
            return
        if (
            not self.last_report_t
            or time.monotonic() - self.last_report_t > 3 * self.interval_s
        ):
            qos.set_fleet_scale(1)

    def build_report(self) -> dict:
        """This replica's coherence state, as one JSON-able dict."""
        state = self.state
        report: dict = {
            "replica": self.replica_id,
            "ts": time.time(),
            # the cadence this replica reports at: the controller sizes
            # its liveness window for the budget-scaling denominator from
            # it (3 intervals, same freshness rule as everywhere else)
            "interval": self.interval_s,
            "ring_hash": "",
            "breakers": {},
            "tenants": {},
        }
        ring = getattr(state.policy, "ring", None)
        if ring is not None and ring.nodes():
            # an EMPTY ring (no session traffic routed yet) reports no
            # hash: an idle replica must not trip the ring-divergence
            # alert against busy ones
            report["ring_hash"] = ring.membership_hash()
        index = getattr(state.policy, "index", None)
        if index is not None:
            report["index"] = index.positions()
        try:
            report["breakers"] = {
                url: snap["state_code"]
                for url, snap in state.breakers.snapshot().items()
            }
        except Exception:  # breakers are optional context, never fatal
            pass
        qos = getattr(state, "qos", None)
        if qos is not None:
            report["tenants"] = qos.totals()
            # this replica admits tenant traffic against local buckets —
            # it belongs in the budget-scaling denominator M (a report-
            # only replica does not: counting it would starve tenants
            # below the global budget)
            report["enforcing"] = True
        pools = self._pool_stats()
        if pools:
            report["pools"] = pools
        return report

    def _pool_stats(self) -> dict:
        """Per-engine pool signals for the controller's rebalancer
        (docs/40-pool-rebalancing.md): live role (scraped tpu:pool_role,
        falling back to the routing policy's static label mapping),
        queue-wait p95 over the last scrape window, decode-seat occupancy,
        and load. Empty when this router has no engine-stats scraper or
        no disaggregated labels — the controller treats absence as "no
        pool signal from this replica"."""
        state = self.state
        scraper = getattr(state, "engine_scraper", None)
        stats = scraper.get_engine_stats() if scraper is not None else {}
        policy = state.policy
        prefill_labels = getattr(policy, "prefill_labels", set()) or set()
        decode_labels = getattr(policy, "decode_labels", set()) or set()
        pools: dict = {}
        try:
            endpoints = state.discovery.endpoints()
        except Exception:
            return pools
        for ep in endpoints:
            s = stats.get(ep.url)
            role = (s.role if s is not None else "") or (
                "prefill" if ep.model_label in prefill_labels
                else "decode" if ep.model_label in decode_labels
                else ""
            )
            if not role and s is None:
                continue  # nothing to say about this engine
            pools[ep.url] = {
                "role": role,
                "queue_wait_p95": s.queue_wait_p95 if s is not None else 0.0,
                "seat_occupancy": s.seat_occupancy if s is not None else 0.0,
                "load": s.load if s is not None else 0.0,
                "model_label": ep.model_label,
            }
        return pools

    async def report_once(self) -> dict:
        """One report round; returns (and stores) the controller reply."""
        sess = await self._http.get()
        async with sess.post(
            self.url + "/fleet/report", json=self.build_report()
        ) as resp:
            reply = await resp.json()
            if resp.status != 200:
                raise RuntimeError(
                    f"fleet endpoint returned HTTP {resp.status}: {reply}"
                )
        self.reports_sent += 1
        self.last_reply = reply
        self.last_report_t = time.monotonic()
        self.last_error = None
        if self.budget_scaling:
            qos = getattr(self.state, "qos", None)
            if qos is not None:
                # the live ENFORCING replica count closes the tenant-
                # budget loop: local buckets enforce a 1/M share so the
                # FLEET admits ~the configured budget. Report-only
                # replicas and rolling-restart leftovers are excluded
                # controller-side (FleetView.enforcing_count); the total
                # replica count is a pre-enforcing-field fallback
                m = reply.get("enforcing_replicas")
                if m is None:
                    m = reply.get("replicas")
                qos.set_fleet_scale(int(m or 1))
        return reply

    def snapshot(self) -> dict:
        """/debug/fleet view of the reporting loop itself."""
        return {
            "url": self.url,
            "interval_s": self.interval_s,
            "budget_scaling": self.budget_scaling,
            "reports_sent": self.reports_sent,
            "report_failures": self.report_failures,
            "last_error": self.last_error,
            "last_report_age_s": (
                round(time.monotonic() - self.last_report_t, 3)
                if self.last_report_t else None
            ),
            "last_reply": self.last_reply,
        }


def debug_fleet_snapshot(state) -> dict:
    """The router's GET /debug/fleet body: this replica's own coherence
    state (ring membership, embedded index positions, breakers, stickiness
    stamps it emits) plus the last fleet-view reply if reporting is on."""
    policy = state.policy
    ring = getattr(policy, "ring", None)
    index = getattr(policy, "index", None)
    body: dict = {
        "replica": getattr(state.args, "router_replica_id", None),
        "policy": type(policy).__name__,
        "ring_hash": ring.membership_hash() if ring is not None else None,
        "ring_nodes": sorted(ring.nodes()) if ring is not None else None,
        "index": index.positions() if index is not None else None,
        "index_convergence": (
            index.convergence.stats() if index is not None else None
        ),
        "breakers": state.breakers.snapshot(),
        "endpoints": [e.url for e in state.discovery.endpoints()],
        "active_streams": state.request_service.active_streams,
        "tenant_budget_scale": (
            state.qos.budget_scale if state.qos is not None else None
        ),
        "fleet_report": (
            state.fleet_reporter.snapshot()
            if getattr(state, "fleet_reporter", None) is not None
            else None
        ),
    }
    return body
