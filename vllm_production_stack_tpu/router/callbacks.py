"""User-supplied request callbacks.

The reference dynamically imports a module exposing `CustomCallbackHandler`
with `pre_request` (may short-circuit a response) and `post_request` hooks
(services/callbacks_service/callbacks.py:23-32). Same contract here; hooks
are awaited, and a non-None return from pre_request is sent to the client
instead of proxying."""

from __future__ import annotations

import importlib
import importlib.util
import inspect
import sys
from pathlib import Path

from ..utils.logging import init_logger

logger = init_logger(__name__)


class CallbackHandler:
    async def pre_request(self, request, body: dict):
        """Return an aiohttp Response to short-circuit, or None to proceed."""
        return None

    async def post_request(self, request, response_body: bytes) -> None:
        return None


class _UserCallbacks(CallbackHandler):
    def __init__(self, impl) -> None:
        self.impl = impl

    async def _call(self, fn, *args):
        if fn is None:
            return None
        out = fn(*args)
        if inspect.isawaitable(out):
            out = await out
        return out

    async def pre_request(self, request, body: dict):
        return await self._call(getattr(self.impl, "pre_request", None), request, body)

    async def post_request(self, request, response_body: bytes) -> None:
        await self._call(
            getattr(self.impl, "post_request", None), request, response_body
        )


def load_callbacks(spec: str | None) -> CallbackHandler | None:
    """`spec` is "module" / "module:Class" / a path to a .py file."""
    if not spec:
        return None
    mod_name, _, cls_name = spec.partition(":")
    if mod_name.endswith(".py"):
        path = Path(mod_name)
        loader_spec = importlib.util.spec_from_file_location(path.stem, path)
        module = importlib.util.module_from_spec(loader_spec)
        sys.modules[path.stem] = module
        loader_spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_name)
    cls = getattr(module, cls_name or "CustomCallbackHandler")
    logger.info("loaded custom callbacks from %s", spec)
    return _UserCallbacks(cls())
