"""Hot-reloadable router configuration.

Watches a YAML/JSON file and, on content change, swaps the app's discovery
and routing policy in place — the reference's DynamicConfigWatcher
(dynamic_config.py:43-288) with an asyncio task instead of a thread. The
current config and a reload counter surface in /health so operators can
confirm a rollout took."""

from __future__ import annotations

import asyncio
import json
from pathlib import Path

import yaml

from ..utils.logging import init_logger

logger = init_logger(__name__)

# keys the watcher understands; anything else in the file is rejected loudly
_ALLOWED = {
    "service_discovery",
    "static_backends",
    "static_models",
    "static_model_labels",
    "routing_logic",
    "session_key",
    "kv_controller_url",
    "kv_aware_threshold",
    "prefill_model_labels",
    "decode_model_labels",
    "model_aliases",
}


def load_config_file(path: str | Path) -> dict:
    text = Path(path).read_text()
    data = (
        json.loads(text)
        if str(path).endswith(".json")
        else yaml.safe_load(text) or {}
    )
    unknown = set(data) - _ALLOWED
    if unknown:
        raise ValueError(f"unknown dynamic config keys: {sorted(unknown)}")
    return data


class DynamicConfigWatcher:
    def __init__(self, path: str, state, interval: float = 10.0):
        self.path = Path(path)
        self.state = state
        self.interval = interval
        self.reload_count = 0
        self.current: dict = {}
        self._last_raw: str | None = None
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            try:
                await self.check_once()
            except Exception as e:
                logger.warning("dynamic config reload failed: %s", e)
            await asyncio.sleep(self.interval)

    async def check_once(self) -> bool:
        """Returns True when a reload was applied."""
        try:
            raw = self.path.read_text()
        except FileNotFoundError:
            return False
        if raw == self._last_raw:
            return False
        config = load_config_file(self.path)
        await self.state.apply_dynamic_config(config)
        self._last_raw = raw
        self.current = config
        self.reload_count += 1
        logger.info("applied dynamic config #%d from %s", self.reload_count, self.path)
        return True
