"""Hot-reloadable router configuration.

Watches a YAML/JSON file and, on content change, swaps the app's discovery
and routing policy in place — the reference's DynamicConfigWatcher
(dynamic_config.py:43-288) with an asyncio task instead of a thread. The
current config and a reload counter surface in /health so operators can
confirm a rollout took."""

from __future__ import annotations

import asyncio
import json
from pathlib import Path

import yaml

from ..utils.logging import init_logger

logger = init_logger(__name__)

# keys the watcher understands; anything else in the file is rejected loudly
_ALLOWED = {
    "service_discovery",
    "static_backends",
    "static_models",
    "static_model_labels",
    "routing_logic",
    "session_key",
    "kv_controller_url",
    "kv_aware_threshold",
    "prefill_model_labels",
    "decode_model_labels",
    "model_aliases",
    # multi-tenant QoS: an inline tenant policy table ({id: {...}}),
    # validated before ANY key of the reload applies — see
    # RouterState.apply_dynamic_config
    "tenants",
}


def load_config_file(path: str | Path) -> dict:
    text = Path(path).read_text()
    data = (
        json.loads(text)
        if str(path).endswith(".json")
        else yaml.safe_load(text) or {}
    )
    unknown = set(data) - _ALLOWED
    if unknown:
        raise ValueError(f"unknown dynamic config keys: {sorted(unknown)}")
    return data


class DynamicConfigWatcher:
    def __init__(
        self,
        path: str,
        state,
        interval: float = 10.0,
        tenant_table_path: str | None = None,
    ):
        # path may be None when the watcher exists only for the tenant
        # table (a router started with --tenant-table-file but no
        # --dynamic-config-file still hot-reloads table edits)
        self.path = Path(path) if path else None
        self.state = state
        self.interval = interval
        self.reload_count = 0
        self.current: dict = {}
        self._last_raw: str | None = None
        self._task: asyncio.Task | None = None
        # multi-tenant QoS: the --tenant-table-file is watched by the SAME
        # loop (one watcher, two files) — edits to the table hot-reload
        # without restarting the router, and a malformed edit keeps the
        # previous table serving (TenantTable validation raises before any
        # swap)
        self.tenant_table_path = (
            Path(tenant_table_path) if tenant_table_path else None
        )
        self._last_tenant_raw: str | None = None
        self.tenant_reload_count = 0

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            try:
                await self.check_once()
            except Exception as e:
                logger.warning("dynamic config reload failed: %s", e)
            await asyncio.sleep(self.interval)

    async def check_once(self) -> bool:
        """Returns True when a reload was applied (either file)."""
        applied = False
        main_err: Exception | None = None
        raw = None
        if self.path is not None:
            try:
                raw = self.path.read_text()
            except FileNotFoundError:
                raw = None
        if raw is not None and raw != self._last_raw:
            try:
                config = load_config_file(self.path)
                await self.state.apply_dynamic_config(config)
            except Exception as e:  # noqa: BLE001 — independence below
                main_err = e
            else:
                self._last_raw = raw
                self.current = config
                self.reload_count += 1
                applied = True
                logger.info(
                    "applied dynamic config #%d from %s",
                    self.reload_count, self.path,
                )
        # tenant table second, INDEPENDENTLY: a persistently broken main
        # config (whose error would otherwise re-raise every poll) must
        # not block an urgent table fix — e.g. revoking a leaked tenant
        # key. Raises on a malformed table — the loop logs it and the
        # PREVIOUS table keeps serving.
        if self.tenant_table_path is not None:
            applied = self._check_tenant_table() or applied
        if main_err is not None:
            raise main_err
        return applied

    def _check_tenant_table(self) -> bool:
        from ..qos import TenantTable

        try:
            raw = self.tenant_table_path.read_text()
        except FileNotFoundError:
            return False
        if raw == self._last_tenant_raw:
            return False
        fmt = "json" if self.tenant_table_path.suffix == ".json" else "yaml"
        table = TenantTable.loads(raw, fmt=fmt)  # raises before any swap
        self.state.apply_tenant_table(table)
        self._last_tenant_raw = raw
        self.tenant_reload_count += 1
        logger.info(
            "applied tenant table #%d from %s (%d tenants)",
            self.tenant_reload_count, self.tenant_table_path, len(table),
        )
        return True
