"""OpenAI-style files service with local-disk storage.

Reference: services/files_service/ (storage.py:20-170, file_storage.py:27-136)
— an abstract Storage with a local-FS impl under a per-user directory, plus
`/v1/files` upload/get/content routes. Same surface here; metadata rides in a
sidecar JSON next to each stored blob."""

from __future__ import annotations

import json
import re
import time
import uuid
from pathlib import Path

from aiohttp import web

_SAFE_COMPONENT = re.compile(r"[A-Za-z0-9._@-]{1,128}")


class FileStorage:
    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    # -- storage ----------------------------------------------------------

    @staticmethod
    def _safe(component: str) -> str:
        """Path components come from client headers/URLs — allow only a flat
        name so neither `..` nor absolute paths can escape the storage root."""
        if not component or not _SAFE_COMPONENT.fullmatch(component):
            raise web.HTTPBadRequest(
                text=json.dumps(
                    {"error": {"message": f"invalid identifier {component!r}"}}
                ),
                content_type="application/json",
            )
        return component

    def _paths(self, user: str, file_id: str) -> tuple[Path, Path]:
        d = self.root / self._safe(user)
        file_id = self._safe(file_id)
        return d / file_id, d / f"{file_id}.json"

    def save(self, user: str, filename: str, content: bytes, purpose: str) -> dict:
        file_id = f"file-{uuid.uuid4().hex[:24]}"
        blob, meta_path = self._paths(user, file_id)
        blob.parent.mkdir(parents=True, exist_ok=True)
        blob.write_bytes(content)
        meta = {
            "id": file_id,
            "object": "file",
            "bytes": len(content),
            "created_at": int(time.time()),
            "filename": filename,
            "purpose": purpose,
        }
        meta_path.write_text(json.dumps(meta))
        return meta

    def get_meta(self, user: str, file_id: str) -> dict | None:
        _, meta_path = self._paths(user, file_id)
        if not meta_path.exists():
            return None
        return json.loads(meta_path.read_text())

    def get_content(self, user: str, file_id: str) -> bytes | None:
        blob, _ = self._paths(user, file_id)
        return blob.read_bytes() if blob.exists() else None

    def list_files(self, user: str) -> list[dict]:
        d = self.root / user
        if not d.exists():
            return []
        return sorted(
            (json.loads(p.read_text()) for p in d.glob("*.json")),
            key=lambda m: m["created_at"],
        )

    def delete(self, user: str, file_id: str) -> bool:
        blob, meta_path = self._paths(user, file_id)
        existed = blob.exists()
        blob.unlink(missing_ok=True)
        meta_path.unlink(missing_ok=True)
        return existed

    # -- routes ------------------------------------------------------------

    def register_routes(self, app: web.Application) -> None:
        app.router.add_post("/v1/files", self.h_upload)
        app.router.add_get("/v1/files", self.h_list)
        app.router.add_get("/v1/files/{file_id}", self.h_get)
        app.router.add_delete("/v1/files/{file_id}", self.h_delete)
        app.router.add_get("/v1/files/{file_id}/content", self.h_content)

    @staticmethod
    def _user(request: web.Request) -> str:
        return request.headers.get("X-User-Id", "anonymous")

    async def h_upload(self, request: web.Request) -> web.Response:
        if not request.content_type.startswith("multipart/"):
            return web.json_response(
                {"error": {"message": "multipart/form-data upload expected"}},
                status=400,
            )
        reader = await request.multipart()
        purpose, filename, content = "batch", "upload", b""
        async for part in reader:
            if part.name == "purpose":
                purpose = (await part.read()).decode()
            elif part.name == "file":
                filename = part.filename or "upload"
                content = await part.read()
        meta = self.save(self._user(request), filename, content, purpose)
        return web.json_response(meta)

    async def h_list(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"object": "list", "data": self.list_files(self._user(request))}
        )

    async def h_get(self, request: web.Request) -> web.Response:
        meta = self.get_meta(self._user(request), request.match_info["file_id"])
        if meta is None:
            return web.json_response(
                {"error": {"message": "file not found"}}, status=404
            )
        return web.json_response(meta)

    async def h_delete(self, request: web.Request) -> web.Response:
        fid = request.match_info["file_id"]
        ok = self.delete(self._user(request), fid)
        return web.json_response(
            {"id": fid, "object": "file", "deleted": ok},
            status=200 if ok else 404,
        )

    async def h_content(self, request: web.Request) -> web.Response:
        content = self.get_content(self._user(request), request.match_info["file_id"])
        if content is None:
            return web.json_response(
                {"error": {"message": "file not found"}}, status=404
            )
        return web.Response(body=content, content_type="application/octet-stream")
