"""Consistent-hash ring for session-sticky routing.

The reference uses the `uhashring` package (routing_logic.py:170-219); this is
a self-contained equivalent: each node owns `replicas` virtual points on a
64-bit ring (xxhash64 of "node#i"), a key maps to the first point clockwise.
Adding/removing a node only remaps the keys that landed on its points — the
property the reference's session-stickiness tests assert
(src/tests/test_session_router.py:24-230).

Fleet determinism contract (docs/34-fleet-routing.md): the ring is a PURE
FUNCTION of its node set. Virtual points derive only from node names, the
point list is kept sorted, and even a 64-bit point collision between two
nodes resolves to the lexicographically-smallest contender rather than to
whichever node happened to be inserted first. Two router replicas whose
discovery views agree therefore compute the identical owner for every
session key regardless of endpoint ARRIVAL ORDER — the invariant the
`membership_hash` divergence alert and the ring-determinism test gate
(tests/test_fleet_scale.py) hold the fleet to. Churn keeps the classic
bounded-remap guarantee: removing a node remaps only keys that landed on
its points (~1/N of traffic), and no key moves between two surviving nodes.
"""

from __future__ import annotations

import bisect

import xxhash


def _h64(s: str) -> int:
    return xxhash.xxh64_intdigest(s)


class HashRing:
    def __init__(self, replicas: int = 120):
        self.replicas = replicas
        self._points: list[int] = []  # sorted virtual-point hashes
        self._owner: dict[int, str] = {}  # point hash -> owning node
        # point hash -> every node hashing to it. 64-bit collisions across
        # distinct nodes are ~impossible, but if one ever happens the owner
        # must not depend on insertion order (two replicas seeing the same
        # endpoints in different arrival orders would route that point's
        # sessions differently): min() of the contenders is order-free.
        self._contenders: dict[int, set[str]] = {}
        self._nodes: set[str] = set()
        self._membership_hash: str | None = None  # cache; add/remove clear

    def nodes(self) -> set[str]:
        return set(self._nodes)

    def membership_hash(self) -> str:
        """Stable digest of the ring's node set (fleet.membership_hash).
        Two router replicas with equal hashes compute identical owners for
        every session key (the virtual points are a pure function of the
        node names); differing hashes mean the same session can route to
        different engines — the divergence the
        tpu:router_ring_membership_hash gauge exists to expose. Cached:
        this sits on the per-request routing path, and membership only
        changes in add_node/remove_node."""
        if self._membership_hash is None:
            from ..fleet import membership_hash

            self._membership_hash = membership_hash(self._nodes)
        return self._membership_hash

    def add_node(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        self._membership_hash = None
        for i in range(self.replicas):
            p = _h64(f"{node}#{i}")
            contenders = self._contenders.get(p)
            if contenders is None:
                # insort only on FIRST sight of the point: two of the SAME
                # node's virtual indices colliding must not duplicate it
                # in _points (sets dedupe the contender, so a len check
                # would insort twice and strand an ownerless copy)
                self._contenders[p] = {node}
                bisect.insort(self._points, p)
            else:
                contenders.add(node)
            self._owner[p] = min(self._contenders[p])

    def remove_node(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._membership_hash = None
        for i in range(self.replicas):
            p = _h64(f"{node}#{i}")
            contenders = self._contenders.get(p)
            if contenders is None or node not in contenders:
                continue
            contenders.discard(node)
            if contenders:
                self._owner[p] = min(contenders)
            else:
                del self._contenders[p]
                del self._owner[p]
                idx = bisect.bisect_left(self._points, p)
                self._points.pop(idx)

    def sync(self, nodes: list[str]) -> None:
        """Converge ring membership to `nodes` (reference
        _update_hash_ring, routing_logic.py:84-103)."""
        target = set(nodes)
        for n in self._nodes - target:
            self.remove_node(n)
        for n in target - self._nodes:
            self.add_node(n)

    def get_node(self, key: str) -> str | None:
        if not self._points:
            return None
        p = _h64(key)
        idx = bisect.bisect_right(self._points, p)
        if idx == len(self._points):
            idx = 0  # wrap
        return self._owner[self._points[idx]]
