"""Consistent-hash ring for session-sticky routing.

The reference uses the `uhashring` package (routing_logic.py:170-219); this is
a self-contained equivalent: each node owns `replicas` virtual points on a
64-bit ring (xxhash64 of "node#i"), a key maps to the first point clockwise.
Adding/removing a node only remaps the keys that landed on its points — the
property the reference's session-stickiness tests assert
(src/tests/test_session_router.py:24-230).
"""

from __future__ import annotations

import bisect

import xxhash


def _h64(s: str) -> int:
    return xxhash.xxh64_intdigest(s)


class HashRing:
    def __init__(self, replicas: int = 120):
        self.replicas = replicas
        self._points: list[int] = []  # sorted virtual-point hashes
        self._owner: dict[int, str] = {}  # point hash -> node
        self._nodes: set[str] = set()
        self._membership_hash: str | None = None  # cache; add/remove clear

    def nodes(self) -> set[str]:
        return set(self._nodes)

    def membership_hash(self) -> str:
        """Stable digest of the ring's node set (fleet.membership_hash).
        Two router replicas with equal hashes compute identical owners for
        every session key (the virtual points are a pure function of the
        node names); differing hashes mean the same session can route to
        different engines — the divergence the
        tpu:router_ring_membership_hash gauge exists to expose. Cached:
        this sits on the per-request routing path, and membership only
        changes in add_node/remove_node."""
        if self._membership_hash is None:
            from ..fleet import membership_hash

            self._membership_hash = membership_hash(self._nodes)
        return self._membership_hash

    def add_node(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        self._membership_hash = None
        for i in range(self.replicas):
            p = _h64(f"{node}#{i}")
            # 64-bit collisions across distinct nodes are ~impossible; keep
            # first owner if one happens so removal stays symmetric
            if p in self._owner:
                continue
            self._owner[p] = node
            bisect.insort(self._points, p)

    def remove_node(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._membership_hash = None
        for i in range(self.replicas):
            p = _h64(f"{node}#{i}")
            if self._owner.get(p) == node:
                del self._owner[p]
                idx = bisect.bisect_left(self._points, p)
                self._points.pop(idx)

    def sync(self, nodes: list[str]) -> None:
        """Converge ring membership to `nodes` (reference
        _update_hash_ring, routing_logic.py:84-103)."""
        target = set(nodes)
        for n in self._nodes - target:
            self.remove_node(n)
        for n in target - self._nodes:
            self.add_node(n)

    def get_node(self, key: str) -> str | None:
        if not self._points:
            return None
        p = _h64(key)
        idx = bisect.bisect_right(self._points, p)
        if idx == len(self._points):
            idx = 0  # wrap
        return self._owner[self._points[idx]]
