"""The proxy hot path: pick an engine, relay the request, stream the reply.

Reference shape (services/request_service/request.py:55-431): parse body →
callbacks.pre_request → rewrite → alias resolution → filter endpoints by
model and sleep state → policy.route → stream relay firing request-stats
hooks (arrival / first byte / completion) → StreamingResponse with
X-Request-Id. Disaggregated prefill adds the 2-phase dance: the same body
with max_tokens=1 goes to a prefill engine (KV lands in its pool and ships
to the decode peer), then the original body streams from a decode engine.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
import uuid

import aiohttp
from aiohttp import web

from ..engine.kv_peer import KV_OWNER_HINT_HEADER
from ..fleet import (
    REPLICA_HEADER,
    RING_HASH_HEADER,
    STICKY_OWNER_HEADER,
    STICKY_SESSION_HEADER,
)
from ..qos.gate import STAMP_HEADERS, TENANT_REQUEST_KEY
from ..tracing import NULL_TRACE, TRACEPARENT_HEADER
from ..utils.jsonio import loads_off_loop
from ..utils.logging import init_logger
from .routing import DisaggregatedPrefillPolicy, RoutingContext, qps_min_url

# per-request slots on the aiohttp request (the router's correlation
# state): the id stamped on every response and forwarded upstream, the
# tracing-spine timeline, and the first-upstream-byte stamp feeding the
# tpu:request_ttft_seconds histogram
RID_KEY = "tpu_request_id"
TRACE_KEY = "tpu_trace"
TTFB_KEY = "tpu_first_byte_mono"
# set by _sever: the response LOOKS like a 200 (headers already went out)
# but the client saw a truncated transfer — the trace must say "severed"
# and the latency histograms must not count it as served
SEVERED_KEY = "tpu_severed"
# the FIRST route() attempt's session-affinity choice ({"session_id",
# "owner", "ring_hash"} from SessionPolicy): kept per-request so failover
# re-picks forward the ORIGINAL ring owner — a delivery that moved off it
# is exactly the stickiness break the engine-side audit counts
STICKY_KEY = "tpu_sticky"
# the FIRST route() attempt's KV route-vs-migrate verdict ({"owner",
# "matched_tokens", "decision"} from KvawarePolicy under
# --kv-migrate-scoring priced): on "migrate" the owner hint is stamped
# upstream (x-kv-owner-hint) so the target engine's hydration planner
# pulls the prefix from the owner instead of rediscovering or recomputing
# it (docs/35-peer-kv-reuse.md)
KV_HINT_KEY = "tpu_kv_hint"


class UpstreamConnectError(Exception):
    """The engine was unreachable BEFORE any byte reached the client —
    the request is safely retryable on another endpoint (nothing was
    streamed, nothing was committed)."""

    def __init__(self, url: str, cause: Exception):
        super().__init__(f"{url}: {cause}")
        self.url = url
        self.cause = cause


class UpstreamDraining(Exception):
    """The engine answered 503 + X-Engine-Draining before any byte reached
    the client: it refused the work without starting it, so failing over is
    exactly as safe as a refused connection — but it is NOT an endpoint
    fault (no breaker strike; discovery drops the pod within a probe
    interval)."""

logger = init_logger(__name__)

# hop-by-hop headers must not be forwarded either direction
_HOP_HEADERS = {
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
    "content-length",
}


def _forward_headers(headers) -> dict[str, str]:
    return {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}


class RequestService:
    """Owns the shared client session and the proxy logic. One instance per
    router app; the app handlers delegate here."""

    def __init__(self, state):
        self.state = state  # RouterState (app.py) — discovery/policy/stats
        self._session: aiohttp.ClientSession | None = None
        # in-flight proxied requests (SSE streams included): the
        # tpu:router_active_streams gauge the 10k-connection bench reads.
        # Plain int mutated only on the event loop — no lock needed.
        self.active_streams = 0

    async def start(self) -> None:
        # config-driven upstream guards (--upstream-total-s /
        # --upstream-sock-read-s). The old hard-coded shape — total=None
        # with no sock_read — left a wedged engine free to hang a client
        # forever; the multipart path's total=300 severed legitimate long
        # transcriptions. sock_read is the streaming-safe guard: active
        # decode emits chunks sub-second, so only a stalled upstream trips
        # it.
        args = self.state.args
        total = getattr(args, "upstream_total_s", 0.0) or None
        sock_read = getattr(args, "upstream_sock_read_s", 300.0) or None
        # --upstream-connector-limit, default unlimited: aiohttp's default
        # connector limit (100) silently serialized every replica behind
        # 100 upstream sockets — the 10k-concurrent-stream target
        # (docs/34-fleet-routing.md) queues at 1% of its concurrency with
        # no error anywhere. fd exhaustion is the real bound; main()
        # raises RLIMIT_NOFILE at boot for exactly this.
        limit = int(getattr(args, "upstream_connector_limit", 0) or 0)
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=limit),
            timeout=aiohttp.ClientTimeout(
                total=total, sock_connect=10, sock_read=sock_read
            )
        )

    async def stop(self) -> None:
        if self._session:
            await self._session.close()

    @property
    def session(self) -> aiohttp.ClientSession:
        assert self._session is not None, "RequestService not started"
        return self._session

    # -- endpoint selection ------------------------------------------------

    def _eligible_endpoints(self, model: str | None) -> list:
        eps = [
            e
            for e in self.state.discovery.endpoints()
            if not e.sleeping and e.healthy
        ]
        if model:
            by_model = [e for e in eps if e.has_model(model)]
            # engines that published no model list yet still count as
            # candidates in static mode (they may simply not be probed)
            eps = by_model or [e for e in eps if not e.model_names]
        # circuit breakers: open endpoints are excluded from policy picks.
        # Fail OPEN when every candidate's breaker is open — the cluster is
        # down or the breakers are wrong, and a connect attempt beats a
        # guaranteed 503.
        breakers = self.state.breakers
        admissible = [e for e in eps if breakers.allow(e.url)]
        return admissible or eps

    def resolve_alias(self, model: str | None) -> str | None:
        if model and model in self.state.model_aliases:
            return self.state.model_aliases[model]
        return model

    # -- the proxy ---------------------------------------------------------

    async def _pair_callbacks(self, request) -> None:
        """post_request pairing for error returns that happen AFTER
        pre_request ran but BEFORE a successful proxy attempt completed —
        plugins doing in-flight accounting / audit-close / rate-limit slot
        release rely on exactly one post_request per pre_request (empty
        body, matching the long-standing 502-path behavior)."""
        if self.state.callbacks is not None:
            await self.state.callbacks.post_request(request, b"")

    # -- multi-tenant QoS (docs/27-multitenancy.md) ------------------------

    def _qos_admit(self, request, body: dict):
        """(tenant_policy, refusal). Per-tenant token buckets + concurrency
        run BEFORE any endpoint is picked — a throttled tenant costs zero
        engine work, zero breaker state, zero queue slots. The 429 carries
        the TENANT's Retry-After (bucket refill time), deliberately
        distinct from the engines' global-shed Retry-After (backlog over
        observed decode throughput). On success the caller MUST call
        _qos_release when the proxy attempt ends (concurrency slot)."""
        qos = self.state.qos
        if qos is None:
            return None, None
        tenant = request.get(TENANT_REQUEST_KEY) or qos.table.default_policy
        verdict = qos.try_admit(tenant, body)
        if verdict is None:
            return tenant, None
        request.get(TRACE_KEY, NULL_TRACE).event(
            "tenant_throttled", tenant=verdict.tenant_id,
            reason=verdict.reason,
            retry_after_s=round(verdict.retry_after_s, 3),
        )
        return tenant, web.json_response(
            {
                "error": {
                    "message": (
                        f"tenant {verdict.tenant_id!r} throttled "
                        f"({verdict.reason}): {verdict.detail}"
                    ),
                    "type": "tenant_throttled",
                    "param": verdict.reason,
                }
            },
            status=429,
            headers={
                "Retry-After": str(
                    max(1, math.ceil(verdict.retry_after_s))
                ),
                "X-Tenant-Id": verdict.tenant_id,
            },
        )

    def _qos_release(self, tenant) -> None:
        if tenant is not None and self.state.qos is not None:
            self.state.qos.release(tenant)

    async def route_openai_request(self, request: web.Request) -> web.StreamResponse:
        """Generic /v1/* proxy with routing. This wrapper owns the
        request's correlation state: the x-request-id echoed on EVERY
        response path (the middleware stamps error short-circuits too),
        the tracing-spine ingress span, and the router-vantage TTFT/E2E
        histogram observations (docs/28-request-tracing.md)."""
        state = self.state
        # normally minted by app.request_id_middleware; the fallback keeps
        # the service usable without the app's middleware stack (tests)
        rid = request.get(RID_KEY) or (
            request.headers.get("X-Request-Id") or uuid.uuid4().hex
        )
        request[RID_KEY] = rid
        trace = state.traces.start(
            rid, "router.ingress",
            traceparent=request.headers.get(TRACEPARENT_HEADER),
            attrs={"path": request.path},
        )
        request[TRACE_KEY] = trace
        t0 = time.monotonic()
        resp: web.StreamResponse | None = None
        raised_status = 500
        self.active_streams += 1
        try:
            if request.content_type == "multipart/form-data":
                # audio transcription (and any multipart upload) routes on
                # the form's `model` field — json.loads on a multipart body
                # can never succeed (reference handles this with a dedicated
                # form-aware path, request.py:513-690)
                resp = await self.route_multipart_request(request)
            else:
                resp = await self._route_json(request)
            return resp
        except web.HTTPException as e:
            # e.g. HTTPRequestEntityTooLarge from request.read(): the trace
            # must carry the real status, not a phantom 500
            raised_status = e.status
            raise
        finally:
            self.active_streams -= 1
            status = resp.status if resp is not None else raised_status
            severed = request.get(SEVERED_KEY, False)
            # latency histograms observe only SERVED requests (refusals
            # answer in microseconds, severed streams truncate early —
            # both would pollute the percentiles); TTFT additionally
            # needs a first upstream byte to have happened. Observed
            # regardless of the tracing flag.
            ttfb = request.get(TTFB_KEY)
            if resp is not None and status < 400 and not severed:
                state.metrics.observe_request(
                    ttft=(ttfb - t0) if ttfb is not None else None,
                    e2e=time.monotonic() - t0,
                    trace_id=trace.trace_id or None,
                )
            state.traces.finish(
                trace,
                status=(
                    "severed" if severed
                    else "ok" if status < 400
                    else f"error:{status}"
                ),
            )

    async def _route_json(self, request: web.Request) -> web.StreamResponse:
        raw = await request.read()
        try:
            # multi-MB prompt bodies parse off the event loop (jsonio) —
            # an inline json.loads here stalls every concurrent stream
            body = await loads_off_loop(raw) if raw else {}
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "request body is not valid JSON"}},
                status=400,
            )
        # QoS first: the cheapest possible refusal (no callbacks, no
        # rewrite, no endpoint work for an over-quota tenant)
        tenant, throttled = self._qos_admit(request, body)
        if throttled is not None:
            return throttled
        try:
            return await self._route_parsed(request, body)
        finally:
            self._qos_release(tenant)

    async def _route_parsed(
        self, request: web.Request, body: dict
    ) -> web.StreamResponse:
        request_id = request.get(RID_KEY) or uuid.uuid4().hex
        if self.state.callbacks is not None:
            short = await self.state.callbacks.pre_request(request, body)
            if short is not None:
                return short
        body = self.state.rewriter.rewrite(request.path, body)
        refused = await self._check_structured(request.path, body)
        if refused is not None:
            return refused

        alias = body.get("model")
        model = self.resolve_alias(alias)
        if model != alias:
            body = {**body, "model": model}
        eps = self._eligible_endpoints(model)
        if not eps:
            await self._pair_callbacks(request)
            return web.json_response(
                {
                    "error": {
                        "message": f"no engine serving model {model!r} is available",
                        "type": "service_unavailable",
                    }
                },
                status=503,
            )

        if isinstance(self.state.policy, DisaggregatedPrefillPolicy):
            return await self._route_disaggregated(request, body, eps, request_id)

        # pre-byte failover (reference behavior is a hard 502; here a dead
        # pod costs one reconnect instead of a failed request): an endpoint
        # that refuses the CONNECTION is dropped from the candidate set and
        # the pick reruns, as long as nothing was streamed to the client
        async def on_exhausted():
            await self._pair_callbacks(request)

        return await self._with_failover(
            eps, request, request_id, body,
            lambda url: self._proxy_stream(request, body, url, request_id),
            on_exhausted=on_exhausted,
        )


    @staticmethod
    async def _check_structured(path: str, body: dict):
        """400 for an uncompilable structured-output surface BEFORE it
        costs an engine round-trip (docs/41-structured-output.md). Runs
        the jax-free structural compile (AST -> byte-DFA with every cap
        enforced) off the event loop — a pathological schema costs real
        milliseconds and must not stall concurrent streams. A schema that
        passes here can still be refused by the engine (vocabulary
        liveness needs the tokenizer), but the common garbage — unknown
        response_format types, unsupported constructs, depth/enum/state
        blowups — dies at the router with a clean client error, never a
        500 and never a wedged stream."""
        if not path.endswith(("/chat/completions", "/completions")):
            return None
        rf = body.get("response_format")
        gj = body.get("guided_json")
        tools = body.get("tools")
        tc = body.get("tool_choice")
        if rf is None and gj is None and not tools:
            return None
        from ..engine.grammar import (
            GrammarCompileError,
            extract_spec,
            tool_choice_spec,
            validate_spec,
        )

        try:
            spec = tool_choice_spec(tools, tc) or extract_spec(rf, gj)
            if spec is not None:
                await asyncio.get_running_loop().run_in_executor(
                    None, validate_spec, spec
                )
        except GrammarCompileError as e:
            return web.json_response(
                {
                    "error": {
                        "message": f"structured output: {e}",
                        "type": "invalid_request_error",
                    }
                },
                status=400,
            )
        return None

    async def _with_failover(self, eps, request, request_id, ctx_body,
                             attempt, on_exhausted=None):
        """Pre-byte failover driver shared by the JSON and multipart proxy
        paths: run `attempt(url)` against the policy's pick; a retry-safe
        connection failure (UpstreamConnectError) either reconnects to the
        SAME endpoint once (stale pooled keep-alive the engine idle-closed
        — evicting would break session/prefix affinity) or evicts it from
        the candidate set and re-picks. Budget 2*len(eps)+1 covers the
        worst case of one stale-reconnect plus one eviction per endpoint.
        A SECOND stale close from the same endpoint stops the failover:
        the server is accepting-then-closing, so the request may have been
        processed and a cross-endpoint resend could double-execute it.

        Endpoint-health memory deliberately stays in discovery (its
        periodic /health probes drop dead pods within one interval); this
        loop only shields the requests that race that window."""
        candidates = list(eps)
        last_err: UpstreamConnectError | None = None
        same_url_retried: set[str] = set()
        attempts = 0
        budget = 2 * len(eps) + 1
        trace = request.get(TRACE_KEY, NULL_TRACE)
        while candidates and attempts < budget:
            attempts += 1
            ctx = RoutingContext(
                endpoints=candidates,
                engine_stats=self.state.engine_scraper.get_engine_stats(),
                request_stats=self.state.request_monitor.get_request_stats(),
                headers=dict(request.headers),
                body=ctx_body,
            )
            try:
                url = await self.state.policy.route(ctx)
            except LookupError as e:
                trace.event("no_endpoints", error=str(e))
                if on_exhausted is not None:
                    await on_exhausted()  # callbacks pairing (see below)
                return web.json_response(
                    {"error": {"message": str(e),
                               "type": "service_unavailable"}},
                    status=503,
                )
            if ctx.sticky is not None and STICKY_KEY not in request:
                # first pick only: the affinity target. Re-picks against a
                # shrunken candidate set (failover) must not rewrite it —
                # the original owner stamp is what lets the engine see
                # that delivery moved (docs/32-fleet-telemetry.md)
                request[STICKY_KEY] = ctx.sticky
            if ctx.kv_hint is not None and KV_HINT_KEY not in request:
                # first pick only, like the sticky stamp: a failover
                # re-pick may land anywhere, but the prefix OWNER doesn't
                # change — whoever serves the request can still pull from
                # it (the owner engine itself just finds the blocks local)
                request[KV_HINT_KEY] = ctx.kv_hint
            logger.info(
                "Routing request %s to %s at %f", request_id, url, time.time()
            )
            trace.event(
                "route", url=url, attempt=attempts,
                policy=type(self.state.policy).__name__,
                candidates=len(candidates),
            )
            self.state.breakers.on_attempt(url)  # reserve half-open probe
            try:
                return await attempt(url)
            except UpstreamConnectError as e:
                last_err = e
                if isinstance(e.cause, UpstreamDraining):
                    # a drain refusal is not an endpoint fault: no breaker
                    # strike, just re-pick among the others
                    candidates = [c for c in candidates if c.url != url]
                    trace.event("failover", url=url, cause="draining")
                    logger.info(
                        "engine %s is draining; request %s fails over "
                        "(%d candidates left)", url, request_id,
                        len(candidates),
                    )
                    continue
                self.state.breakers.on_failure(url)
                trace.event(
                    "failover", url=url, cause=type(e.cause).__name__,
                )
                if isinstance(e.cause, aiohttp.ServerDisconnectedError):
                    if url not in same_url_retried:
                        same_url_retried.add(url)
                        logger.info(
                            "stale connection to %s for %s — reconnecting",
                            url, request_id,
                        )
                        continue
                    break  # repeated accept-then-close: don't resend
                candidates = [c for c in candidates if c.url != url]
                logger.warning(
                    "engine %s refused connection for %s — failing over "
                    "(%d candidates left)", url, request_id, len(candidates),
                )
        trace.event("exhausted", attempts=attempts)
        if on_exhausted is not None:
            await on_exhausted()
        if last_err is not None and isinstance(last_err.cause, UpstreamDraining):
            # every candidate politely refused (overlapping drain windows in
            # a rolling restart): the engines are healthy and coming back —
            # tell the client to retry, don't report them unreachable
            return web.json_response(
                {"error": {"message": "all candidate engines are draining; "
                                      "retry shortly",
                           "type": "service_unavailable"}},
                status=503,
                headers={"Retry-After": "2"},
            )
        return web.json_response(
            {"error": {"message": f"engine unreachable: {last_err}"}},
            status=502,
        )

    async def route_multipart_request(
        self, request: web.Request
    ) -> web.StreamResponse:
        """Multipart proxy for /v1/audio/transcriptions-class endpoints:
        parse the form, route on its `model` field (preferring engines
        labeled `transcription` when any carry labels), rebuild the form with
        a fresh boundary, and relay the reply. Mirrors the reference's
        form-aware path (request.py:513-690) on aiohttp primitives."""
        request_id = request.get(RID_KEY) or (
            request.headers.get("X-Request-Id") or uuid.uuid4().hex
        )
        form = await request.post()
        for required in ("file", "model"):
            if required not in form:
                return web.json_response(
                    {
                        "error": {
                            "message": f"missing '{required}' in form data"
                        }
                    },
                    status=400,
                )
        alias = form["model"]
        model = self.resolve_alias(alias if isinstance(alias, str) else None)
        eps = self._eligible_endpoints(model)
        labeled = [e for e in eps if e.model_label == "transcription"]
        if labeled:
            eps = labeled
        if not eps:
            return web.json_response(
                {
                    "error": {
                        "message": f"no transcription backend for model {model!r}",
                        "type": "not_found",
                    }
                },
                status=404,
            )
        # buffer file fields ONCE: FormData is single-use, and a failover
        # retry must resend identical bytes (FileField.read() drains)
        fields = []
        for key, value in form.items():
            if isinstance(value, web.FileField):
                fields.append((key, value.file.read(), value.filename,
                               value.content_type))
            elif key == "model":
                fields.append((key, model or "", None, None))
            else:
                fields.append((key, value, None, None))
        mon = self.state.request_monitor

        async def attempt(url: str) -> web.StreamResponse:
            # headers built PER ATTEMPT, after route() ran: the sticky
            # stamps (request[STICKY_KEY], set by _with_failover on the
            # first pick) and the decaying deadline must reflect this
            # attempt — a once-built dict predates routing and silently
            # dropped the stamps for all multipart session traffic. The
            # original Content-Type names the OLD boundary — aiohttp sets
            # the fresh one for the rebuilt form.
            headers = {
                k: v
                for k, v in self._upstream_headers(request).items()
                if k.lower() != "content-type"
            }
            # fresh FormData per attempt from the buffered fields — the
            # object is single-use and a retry must resend identical bytes
            fd = aiohttp.FormData()
            for key, payload, filename, ctype in fields:
                if filename is not None:
                    fd.add_field(key, payload, filename=filename,
                                 content_type=ctype)
                else:
                    fd.add_field(key, payload)
            mon.on_new_request(url, request_id, time.time())
            resp: web.StreamResponse | None = None
            try:
                # no per-request timeout override: the session's
                # config-driven guards apply (the old total=300 here
                # severed legitimate long transcriptions; sock_read is the
                # wedged-engine guard)
                async with self.session.post(
                    url + request.path,
                    data=fd,
                    headers=headers,
                ) as upstream:
                    if (
                        upstream.status == 503
                        and upstream.headers.get("X-Engine-Draining")
                    ):
                        raise UpstreamConnectError(url, UpstreamDraining())
                    if upstream.status < 500:
                        # a 5xx is not proof of health: it must not reset
                        # strikes from real mid-stream deaths (an engine
                        # alternating instant-500s with dying would never
                        # trip its breaker) — but nor is it a strike (a
                        # model error is not a flapping endpoint)
                        self.state.breakers.on_success(url)
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            resp.headers[k] = v
                    resp.headers["X-Request-Id"] = request_id
                    await resp.prepare(request)
                    first = True
                    async for chunk in upstream.content.iter_any():
                        if first:
                            first = False
                            mon.on_first_token(url, request_id, time.time())
                            if TTFB_KEY not in request:
                                request[TTFB_KEY] = time.monotonic()
                                request.get(TRACE_KEY, NULL_TRACE).event(
                                    "first_byte", url=url
                                )
                        await resp.write(chunk)
                    await resp.write_eof()
                    return resp
            except (aiohttp.ClientConnectorError,
                    aiohttp.ConnectionTimeoutError,
                    aiohttp.ServerDisconnectedError) as e:
                if resp is None or not resp.prepared:
                    # connection never carried the request (or a stale
                    # keep-alive closed before headers): retry-safe
                    raise UpstreamConnectError(url, e) from e
                return await self._sever(request, resp, url, request_id, e)
            except aiohttp.ClientError as e:
                # the upload may have been RECEIVED (e.g. the engine died
                # mid-processing): never resend non-idempotent work
                if resp is None or not resp.prepared:
                    # same breaker accounting as the JSON path's pre-headers
                    # death (_sever strikes for the prepared case)
                    self.state.breakers.on_failure(url)
                    return web.json_response(
                        {"error": {"message": f"engine error: {e}"}},
                        status=502,
                    )
                return await self._sever(request, resp, url, request_id, e)
            finally:
                mon.on_request_complete(url, request_id, time.time())

        # QoS: requests-per-second + concurrency only (multipart bodies
        # carry audio, not a token-meterable prompt)
        tenant, throttled = self._qos_admit(request, {"model": model})
        if throttled is not None:
            return throttled
        try:
            return await self._with_failover(
                eps, request, request_id, {"model": model}, attempt,
            )
        finally:
            self._qos_release(tenant)


    _DEADLINE_KEY = "tpu_deadline_abs"  # per-request slot on the aiohttp req

    def _upstream_headers(self, request) -> dict[str, str]:
        """Forwardable headers, with the relative x-request-deadline-ms
        budget DECAYED by router-side elapsed time (the client's header, or
        --default-deadline-ms when absent). The budget is anchored to an
        absolute monotonic deadline on first build, so a failover attempt
        after a 10 s connect timeout forwards the 10-seconds-poorer
        remainder instead of re-arming the full budget on every retry."""
        headers = _forward_headers(request.headers)
        # correlation: the generated/echoed x-request-id rides upstream so
        # the engine's spans and logs key on the SAME id, and the tracing
        # spine's W3C traceparent (this router's ingress span as parent)
        # joins the engine's timeline into one trace
        rid = request.get(RID_KEY)
        if rid:
            headers["X-Request-Id"] = rid
        trace = request.get(TRACE_KEY)
        if trace is not None:
            tp = trace.child_traceparent()
            if tp:
                headers[TRACEPARENT_HEADER] = tp
        # fleet-coherence stamps (docs/32-fleet-telemetry.md): which router
        # replica proxied this request, and — for session traffic — the
        # ring-chosen owner + ring membership hash the engine-side
        # stickiness audit compares across a session's requests. Inbound
        # copies are dropped whenever this router stamps (a client must
        # not be able to fabricate violations); a replica with no id and
        # no session policy stays transparent, like the tenant stamps.
        replica_id = getattr(self.state.args, "router_replica_id", None)
        sticky = request.get(STICKY_KEY)
        if replica_id or sticky is not None:
            fleet_headers = (
                REPLICA_HEADER, STICKY_SESSION_HEADER,
                STICKY_OWNER_HEADER, RING_HASH_HEADER,
            )
            headers = {
                k: v for k, v in headers.items()
                if k.lower() not in fleet_headers
            }
        if replica_id:
            headers[REPLICA_HEADER] = str(replica_id)
        if sticky is not None:
            headers[STICKY_SESSION_HEADER] = sticky["session_id"]
            headers[STICKY_OWNER_HEADER] = sticky["owner"]
            headers[RING_HASH_HEADER] = sticky["ring_hash"]
        # peer-tier owner hint (docs/35-peer-kv-reuse.md): inbound copies
        # are ALWAYS dropped, under EVERY policy — a client must never be
        # able to point an engine's KV fetcher at an arbitrary "owner"
        # (unlike the tenant stamps there is no trusted-upstream-gateway
        # passthrough case: any gateway that can legitimately stamp this
        # is itself a KV-aware router sitting closer to the engines) —
        # and re-stamped only when this request's priced scoring actually
        # chose migrate
        headers = {
            k: v for k, v in headers.items()
            if k.lower() != KV_OWNER_HINT_HEADER
        }
        kv_hint = request.get(KV_HINT_KEY)
        if kv_hint is not None and kv_hint.get("decision") == "migrate":
            headers[KV_OWNER_HINT_HEADER] = kv_hint["owner"]
        qos = self.state.qos
        if qos is not None:
            # spoof-proofing: with QoS active, inbound x-tenant-id /
            # x-priority / x-tenant-weight are ALWAYS dropped — the only
            # stamps an engine sees are the ones this router resolved from
            # its table. (Without a table the router is transparent, so an
            # upstream gateway may stamp through it.)
            headers = {
                k: v
                for k, v in headers.items()
                if k.lower() not in STAMP_HEADERS
            }
            policy = request.get(TENANT_REQUEST_KEY)
            if policy is not None:
                qos.stamp(headers, policy)
        abs_deadline = request.get(self._DEADLINE_KEY)
        if abs_deadline is None:
            ms = 0.0
            raw = request.headers.get("x-request-deadline-ms")
            if raw:
                try:
                    ms = float(raw)
                except (TypeError, ValueError):
                    ms = 0.0
            if ms <= 0:
                ms = getattr(self.state.args, "default_deadline_ms", 0.0)
            # 0.0 = no deadline (sentinel, so the parse runs once)
            abs_deadline = (
                time.monotonic() + ms / 1000.0 if ms and ms > 0 else 0.0
            )
            request[self._DEADLINE_KEY] = abs_deadline
        if abs_deadline:
            # clamp to 1 ms: an exhausted budget must still reach the
            # engine as an immediately-expired deadline (clean admission
            # 503), not vanish (deadline_from_headers ignores <= 0)
            remaining_ms = max(
                1, int((abs_deadline - time.monotonic()) * 1000)
            )
            headers["x-request-deadline-ms"] = str(remaining_ms)
        return headers

    async def _sever(self, request, resp, backend_url, request_id, e):
        """Headers (and possibly chunks) already went out — the only
        honest signal left is severing the connection so the client sees
        a truncated transfer instead of a clean end. Counts as a breaker
        failure: an engine dying mid-stream is exactly the flapping the
        breaker exists to remember."""
        logger.warning(
            "engine %s died mid-stream for request %s: %s",
            backend_url, request_id, e,
        )
        request[SEVERED_KEY] = True
        request.get(TRACE_KEY, NULL_TRACE).event(
            "severed", url=backend_url, cause=type(e).__name__
        )
        # goodput signal path (docs/29-saturation-slo.md): the engine that
        # produced this stream died with it, so its ledger can't classify
        # the tokens — the router's request-level counter is the record
        self.state.metrics.severed_streams.inc()
        self.state.breakers.on_failure(backend_url)
        resp.force_close()
        if request.transport is not None:
            request.transport.close()
        return resp

    async def _proxy_stream(
        self,
        request: web.Request,
        body: dict,
        backend_url: str,
        request_id: str,
    ) -> web.StreamResponse:
        mon = self.state.request_monitor
        trace = request.get(TRACE_KEY, NULL_TRACE)
        data = json.dumps(body).encode()
        mon.on_new_request(backend_url, request_id, time.time())
        pre_byte_raise = False
        cacheable = (
            self.state.semantic_cache is not None
            and request.path == "/v1/chat/completions"
            and not body.get("stream")
        )
        # only buffer the reply when something will actually consume it —
        # otherwise N concurrent long streams double the router's memory
        want_body = cacheable or self.state.callbacks is not None
        full = bytearray()
        resp: web.StreamResponse | None = None
        try:
            async with self.session.request(
                request.method,
                backend_url + request.path,
                headers=self._upstream_headers(request),
                data=data,
            ) as upstream:
                if (
                    upstream.status == 503
                    and upstream.headers.get("X-Engine-Draining")
                ):
                    # the engine refused without starting the work — as
                    # retry-safe as a refused connection, and not a fault
                    # (no breaker strike; discovery drops the pod within a
                    # probe interval)
                    pre_byte_raise = True
                    raise UpstreamConnectError(
                        backend_url, UpstreamDraining()
                    )
                if upstream.status < 500:
                    # same rule as the multipart path: a 5xx neither
                    # resets breaker strikes nor adds one
                    self.state.breakers.on_success(backend_url)
                trace.event(
                    "upstream_status", status=upstream.status,
                    url=backend_url,
                )
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        resp.headers[k] = v
                resp.headers["X-Request-Id"] = request_id
                await resp.prepare(request)
                first = True
                # inter-token latency at the ROUTER vantage: the gap
                # between consecutive streamed chunks is the client's TPOT
                # (tpu:request_itl_seconds) — observed only on streaming
                # requests, where one chunk ~= one token delta
                observe_itl = bool(body.get("stream"))
                last_chunk_t = 0.0
                async for chunk in upstream.content.iter_any():
                    now_mono = time.monotonic()
                    if first:
                        first = False
                        mon.on_first_token(backend_url, request_id, time.time())
                        if TTFB_KEY not in request:
                            request[TTFB_KEY] = now_mono
                            trace.event("first_byte", url=backend_url)
                    elif observe_itl:
                        self.state.metrics.observe_itl(
                            now_mono - last_chunk_t
                        )
                    last_chunk_t = now_mono
                    if want_body:
                        full.extend(chunk)
                    await resp.write(chunk)
                await resp.write_eof()
                if cacheable and upstream.status == 200:
                    try:
                        await self.state.semantic_cache.store(
                            body, await loads_off_loop(bytes(full))
                        )
                    except (json.JSONDecodeError, UnicodeDecodeError):
                        pass
                return resp
        except (aiohttp.ClientConnectorError,
                aiohttp.ConnectionTimeoutError,
                aiohttp.ServerDisconnectedError) as e:
            if resp is None or not resp.prepared:
                # the connection never carried the request (refused /
                # timed out during CONNECT / unreachable / stale
                # keep-alive closed before headers):
                # nothing reached client OR engine — the caller can fail
                # over safely (_with_failover)
                pre_byte_raise = True
                raise UpstreamConnectError(backend_url, e) from e
            return await self._sever(request, resp, backend_url,
                                     request_id, e)
        except aiohttp.ClientError as e:
            if resp is None or not resp.prepared:
                # the request MAY have been received and processed (engine
                # died mid-inference before sending headers): a resend
                # could double-execute non-idempotent work — fail honestly
                self.state.breakers.on_failure(backend_url)
                return web.json_response(
                    {"error": {"message": f"engine error: {e}"}}, status=502
                )
            return await self._sever(request, resp, backend_url,
                                     request_id, e)
        finally:
            mon.on_request_complete(backend_url, request_id, time.time())
            if self.state.callbacks is not None and not pre_byte_raise:
                await self.state.callbacks.post_request(request, bytes(full))

    # -- disaggregated prefill --------------------------------------------

    async def _route_disaggregated(
        self,
        request: web.Request,
        body: dict,
        eps: list,
        request_id: str,
    ) -> web.StreamResponse:
        """2-phase: run the prompt on a prefill engine with max_tokens=1 (its
        KV pages ship to the decode peer), then stream the real request from
        a decode engine (reference request.py:339-431)."""
        policy: DisaggregatedPrefillPolicy = self.state.policy
        # live-advertised roles when the stats scraper has them, static
        # labels otherwise (docs/40-pool-rebalancing.md)
        prefill_eps, decode_eps = policy.pools(
            eps, self.state.engine_scraper.get_engine_stats()
        )
        if not prefill_eps or not decode_eps:
            await self._pair_callbacks(request)
            return web.json_response(
                {"error": {"message": "prefill/decode pools are not both available"}},
                status=503,
            )
        stats = self.state.request_monitor.get_request_stats()
        prefill_body = {**body, "max_tokens": 1, "stream": False}
        # pick within each pool directly: routing by body inspection would
        # misfile a legitimate client max_tokens=1 request in the decode phase.
        # Both hops treat a drain refusal (503 + X-Engine-Draining) as a
        # re-pick, not a fault — during a pool role flip (docs/40) the
        # target drains while still carrying its old role, and clients
        # must never see the refusal.
        prefill_url = None
        prefill_candidates = list(prefill_eps)
        last_draining = False
        t0 = time.time()
        while prefill_candidates:
            url = qps_min_url(prefill_candidates, stats)
            try:
                async with self.session.post(
                    url + request.path,
                    json=prefill_body,
                    # _upstream_headers, not the raw forward: the prefill hop
                    # must strip inbound tenant/fleet stamp spoofs and carry
                    # the same rid/traceparent/deadline the decode hop gets —
                    # a client could otherwise fabricate stickiness violations
                    # through the prefill engine's audit
                    headers=self._upstream_headers(request),
                ) as resp:
                    await resp.read()
                    if (resp.status == 503
                            and resp.headers.get("X-Engine-Draining")):
                        last_draining = True
                        prefill_candidates = [
                            c for c in prefill_candidates if c.url != url
                        ]
                        continue
                    if resp.status != 200:
                        await self._pair_callbacks(request)
                        return web.json_response(
                            {"error": {"message": f"prefill engine returned {resp.status}"}},
                            status=502,
                        )
                    prefill_url = url
                    break
            except aiohttp.ClientError as e:
                last_draining = False
                last_err = e
                prefill_candidates = [
                    c for c in prefill_candidates if c.url != url
                ]
        if prefill_url is None:
            await self._pair_callbacks(request)
            if last_draining:
                return web.json_response(
                    {"error": {"message": "all prefill engines are draining; "
                                          "retry shortly",
                               "type": "service_unavailable"}},
                    status=503,
                    headers={"Retry-After": "2"},
                )
            return web.json_response(
                {"error": {"message": f"prefill engine unreachable: {last_err}"}},
                status=502,
            )
        logger.info(
            "PD prefill for %s on %s took %.3fs", request_id, prefill_url, time.time() - t0
        )
        decode_url = qps_min_url(decode_eps, stats)
        # ship the prompt's KV pages prefill->decode (content-addressed
        # export/adopt, engine/kv_transfer.py — the NIXL-equivalent hop). A
        # failed transfer degrades to recompute on the decode engine, so it
        # logs rather than fails the request.
        pull_body = {"source_url": prefill_url}
        if body.get("model"):
            # the engines salt KV chains per LoRA adapter (model field);
            # omitting it would make adapter exports walk the base chain
            # and ship nothing
            pull_body["model"] = body["model"]
        if "messages" in body:
            pull_body["messages"] = body["messages"]
        elif "prompt" in body:
            p = body["prompt"]
            if isinstance(p, str):
                pull_body["text"] = p
            elif isinstance(p, list) and p and isinstance(p[0], int):
                pull_body["token_ids"] = p
            elif isinstance(p, list) and len(p) == 1 and isinstance(p[0], str):
                pull_body["text"] = p[0]
        decode_candidates = list(decode_eps)
        while True:
            try:
                async with self.session.post(
                    decode_url + "/kv/pull", json=pull_body,
                    timeout=aiohttp.ClientTimeout(total=30),
                ) as resp:
                    if resp.status == 200:
                        logger.info(
                            "PD KV transfer for %s: %s -> %s: %s",
                            request_id, prefill_url, decode_url,
                            await resp.json(),
                        )
                    else:
                        logger.warning(
                            "PD KV transfer for %s returned %d (%s); decode "
                            "will recompute",
                            request_id, resp.status, await resp.text(),
                        )
            except Exception as e:  # ANY transfer fault degrades to recompute
                logger.warning(
                    "PD KV transfer failed (%s); decode will recompute", e
                )
            logger.info("Routing request %s to %s at %f", request_id, decode_url, time.time())
            try:
                return await self._proxy_stream(
                    request, body, decode_url, request_id
                )
            except UpstreamConnectError as e:
                if isinstance(e.cause, UpstreamDraining):
                    # a drain refusal lands before any work starts, so a
                    # re-pick is retry-safe; the shipped KV stays behind
                    # and the new pick recomputes — a slower first token
                    # beats a client-visible refusal mid-flip
                    decode_candidates = [
                        c for c in decode_candidates if c.url != decode_url
                    ]
                    if decode_candidates:
                        decode_url = qps_min_url(decode_candidates, stats)
                        continue
                    await self._pair_callbacks(request)
                    return web.json_response(
                        {"error": {"message": "all decode engines are "
                                              "draining; retry shortly",
                                   "type": "service_unavailable"}},
                        status=503,
                        headers={"Retry-After": "2"},
                    )
                # the shipped KV lives on THIS decode engine — a blind retry
                # elsewhere would silently recompute; surface the failure
                await self._pair_callbacks(request)
                return web.json_response(
                    {"error": {"message": f"decode engine unreachable: {e}"}},
                    status=502,
                )

    # -- sleep / wake control ---------------------------------------------

    async def sleep_control(
        self, request: web.Request, action: str
    ) -> web.Response:
        """Proxy /sleep, /wake_up, /is_sleeping to a chosen engine and track
        its sleep flag for routing filters (reference request.py:434-510)."""
        url = request.query.get("url") or request.headers.get("X-Engine-Url")
        eps = self.state.discovery.endpoints()
        if url is None and len(eps) == 1:
            url = eps[0].url
        if url is None or not any(e.url == url for e in eps):
            return web.json_response(
                {"error": {"message": "specify a known engine with ?url="}},
                status=400,
            )
        try:
            if action == "is_sleeping":
                async with self.session.get(url + "/is_sleeping") as resp:
                    return web.json_response(await resp.json(), status=resp.status)
            level = request.query.get("level", "1")
            async with self.session.post(
                f"{url}/{action}", params={"level": level}
            ) as resp:
                payload = await resp.json()
            if resp.status == 200:
                self.state.discovery.set_sleeping(url, action == "sleep")
            return web.json_response(payload, status=resp.status)
        except aiohttp.ClientError as e:
            return web.json_response(
                {"error": {"message": f"engine unreachable: {e}"}}, status=502
            )
