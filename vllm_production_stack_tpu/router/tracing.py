"""Optional tracing/error reporting (reference: Sentry init in app.py:123-130
+ parser.py:341-359; OTel→Jaeger via engine env, tutorial 12).

Both integrations are soft dependencies: if the SDK isn't installed the
flags log a warning and no-op, so the router never gains a hard dependency
on an APM stack. Engine-side traces come from the engines themselves (set
OTEL_EXPORTER_OTLP_ENDPOINT on engine pods — JAX/XLA profiles via xprof are
the device-level complement, SURVEY §5)."""

from __future__ import annotations

from ..utils.logging import init_logger

logger = init_logger(__name__)


def init_sentry(dsn: str | None, traces_sample_rate: float = 0.0,
                profiles_sample_rate: float = 0.0) -> bool:
    """Initialize Sentry if a DSN is configured and the SDK is available."""
    if not dsn:
        return False
    try:
        import sentry_sdk
    except ImportError:
        logger.warning(
            "--sentry-dsn set but sentry-sdk is not installed; "
            "error reporting disabled"
        )
        return False
    sentry_sdk.init(
        dsn=dsn,
        traces_sample_rate=traces_sample_rate,
        profiles_sample_rate=profiles_sample_rate,
    )
    logger.info("sentry initialized (traces %.2f, profiles %.2f)",
                traces_sample_rate, profiles_sample_rate)
    return True


def init_otel(service_name: str = "tpu-stack-router") -> bool:
    """Initialize OpenTelemetry trace export if the SDK is available and
    OTEL_EXPORTER_OTLP_ENDPOINT is set (standard OTel env contract)."""
    import os

    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
    if not endpoint:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        logger.warning(
            "OTEL_EXPORTER_OTLP_ENDPOINT set but the opentelemetry SDK is "
            "not installed; tracing disabled"
        )
        return False
    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    logger.info("OTLP tracing to %s", endpoint)
    return True
