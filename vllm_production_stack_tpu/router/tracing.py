"""Optional external tracing/error-reporting SDK initialization (reference:
Sentry init in app.py:123-130 + parser.py:341-359; OTel→Jaeger via engine
env, tutorial 12).

The spans themselves come from the dependency-free tracing spine
(vllm_production_stack_tpu/tracing, docs/28-request-tracing.md): the router
records an ingress span per proxied request (routing decision, failover
attempts, QoS verdict, upstream TTFB) and the engines record
queue/prefill/decode spans joined by the propagated traceparent — all
in-process, served by /debug/requests, with or without any SDK. What THIS
module does is wire the optional export paths: `init_otel` installs an OTLP
TracerProvider so the spine's finished timelines also ship to a
Jaeger/Tempo-class backend (tracing/otel.py bridges them), and
`init_sentry` enables error reporting. Both are soft dependencies: without
the SDK the flags log a warning and no-op — the router never gains a hard
dependency on an APM stack. JAX/XLA device profiles are the engine-side
complement (POST /debug/profile/start on a live engine, SURVEY §5)."""

from __future__ import annotations

from ..utils.logging import init_logger

logger = init_logger(__name__)


def init_sentry(dsn: str | None, traces_sample_rate: float = 0.0,
                profiles_sample_rate: float = 0.0) -> bool:
    """Initialize Sentry if a DSN is configured and the SDK is available."""
    if not dsn:
        return False
    try:
        import sentry_sdk
    except ImportError:
        logger.warning(
            "--sentry-dsn set but sentry-sdk is not installed; "
            "error reporting disabled"
        )
        return False
    sentry_sdk.init(
        dsn=dsn,
        traces_sample_rate=traces_sample_rate,
        profiles_sample_rate=profiles_sample_rate,
    )
    logger.info("sentry initialized (traces %.2f, profiles %.2f)",
                traces_sample_rate, profiles_sample_rate)
    return True


def init_otel(service_name: str = "tpu-stack-router") -> bool:
    """Initialize OpenTelemetry trace export if the SDK is available and
    OTEL_EXPORTER_OTLP_ENDPOINT is set (standard OTel env contract). With
    a provider installed, the tracing spine's finished request timelines
    export through it (tracing/otel.py) — same ids as /debug/requests, so
    router and engine spans join into one trace in the backend."""
    import os

    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
    if not endpoint:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        logger.warning(
            "OTEL_EXPORTER_OTLP_ENDPOINT set but the opentelemetry SDK is "
            "not installed; tracing disabled"
        )
        return False
    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    logger.info("OTLP tracing to %s", endpoint)
    return True
