"""PII screening middleware (feature gate: PIIDetection).

Blocks requests whose prompt text contains detectable PII, mirroring the
reference's analyzer set (experimental/pii/analyzers/): the built-in regex
analyzer — email, phone, SSN, credit card (Luhn-checked), IP address,
API-key-shaped secrets — plus an optional Presidio-backed analyzer
(reference analyzers/presidio.py) behind a soft import: NER-grade entity
recognition when `presidio-analyzer` is installed in the router image,
clean error if selected without it."""

from __future__ import annotations

import json
import re
from dataclasses import dataclass

from aiohttp import web

from ..utils.jsonio import loads_off_loop
from ..utils.logging import init_logger

logger = init_logger(__name__)


@dataclass(frozen=True)
class PIIMatch:
    category: str
    span: tuple[int, int]


_PATTERNS: dict[str, re.Pattern] = {
    "email": re.compile(r"\b[\w.+-]+@[\w-]+\.[\w.-]{2,}\b"),
    "phone": re.compile(
        r"(?<![\w.])(?:\+?\d{1,2}[\s.-]?)?(?:\(\d{3}\)|\d{3})[\s.-]\d{3}[\s.-]\d{4}\b"
    ),
    "ssn": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "credit_card": re.compile(r"\b(?:\d[ -]?){13,19}\b"),
    "ip_address": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "secret_key": re.compile(r"\b(?:sk|pk|api|key)[-_][A-Za-z0-9_-]{16,}\b"),
}


def _luhn_ok(digits: str) -> bool:
    ds = [int(c) for c in digits if c.isdigit()]
    if not 13 <= len(ds) <= 19:
        return False
    total = 0
    for i, d in enumerate(reversed(ds)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


class RegexAnalyzer:
    def analyze(self, text: str) -> list[PIIMatch]:
        found = []
        for cat, pat in _PATTERNS.items():
            for m in pat.finditer(text):
                if cat == "credit_card" and not _luhn_ok(m.group()):
                    continue
                found.append(PIIMatch(cat, m.span()))
        return found


class PresidioAnalyzer:
    """Microsoft Presidio NER analyzer (reference
    experimental/pii/analyzers/presidio.py): statistical entity
    recognition on top of what the regexes catch. Soft dependency — the
    constructor raises a clear error when the package is absent so a
    misconfigured deployment fails at startup, not per-request."""

    def __init__(self, score_threshold: float = 0.5, language: str = "en"):
        try:
            from presidio_analyzer import AnalyzerEngine
        except ImportError as e:
            raise RuntimeError(
                "--pii-analyzer presidio needs the presidio-analyzer "
                "package in the router image (pip install "
                "presidio-analyzer)"
            ) from e
        self._engine = AnalyzerEngine()
        self.score_threshold = score_threshold
        self.language = language

    def analyze(self, text: str) -> list[PIIMatch]:
        results = self._engine.analyze(text=text, language=self.language)
        return [
            PIIMatch(r.entity_type.lower(), (r.start, r.end))
            for r in results
            if r.score >= self.score_threshold
        ]


ANALYZERS = {
    "regex": RegexAnalyzer,
    "presidio": PresidioAnalyzer,
}


def make_analyzer(name: str):
    if name not in ANALYZERS:
        raise ValueError(
            f"unknown PII analyzer {name!r}; expected one of "
            f"{sorted(ANALYZERS)}"
        )
    return ANALYZERS[name]()


class PIIMiddleware:
    def __init__(self, analyzer=None):
        self.analyzer = analyzer or RegexAnalyzer()
        self.blocked_total = 0
        # Offloading keeps the event loop free. Worker count depends on the
        # analyzer: Presidio's shared spaCy pipeline is not safe for
        # concurrent calls, so it gets ONE serializing worker. The regex
        # analyzer is GIL-bound either way (sre holds the GIL), so extra
        # threads add no matching throughput — the small pool only stops one
        # pathologically large prompt from head-of-line-blocking every other
        # request's analysis behind it.
        from concurrent.futures import ThreadPoolExecutor

        # only the known-reentrant regex analyzer gets concurrency; any
        # injected analyzer defaults to the safe serialized path
        workers = 4 if isinstance(self.analyzer, RegexAnalyzer) else 1
        self._executor = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="pii-analyzer"
        )

    async def check(self, request: web.Request) -> web.Response | None:
        """Returns a 400 response when PII is found, else None."""
        raw = await request.read()
        try:
            body = await loads_off_loop(raw)
        except json.JSONDecodeError:
            return None
        texts = []
        for m in body.get("messages", []):
            c = m.get("content")
            if isinstance(c, str):
                texts.append(c)
        p = body.get("prompt")
        if isinstance(p, str):
            texts.append(p)
        elif isinstance(p, list):
            texts.extend(str(x) for x in p)
        import asyncio

        # off the event loop: Presidio's NER pass is tens-to-hundreds of
        # ms of CPU-bound work per request (regex is cheap, but large
        # prompts aren't free either) — running it inline would stall
        # every in-flight stream
        matches = await asyncio.get_running_loop().run_in_executor(
            self._executor, self.analyzer.analyze, "\n".join(texts)
        )
        if not matches:
            return None
        self.blocked_total += 1
        cats = sorted({m.category for m in matches})
        logger.info("blocked request containing PII: %s", cats)
        return web.json_response(
            {
                "error": {
                    "message": f"request blocked: detected PII ({', '.join(cats)})",
                    "type": "pii_detected",
                    "categories": cats,
                }
            },
            status=400,
        )
