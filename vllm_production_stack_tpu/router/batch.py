"""OpenAI-style batch API.

Jobs land in a SQLite queue and a background task executes each JSONL line as
a real routed request through the router's own proxy machinery, writing an
output file with per-line responses. The reference keeps the same queue shape
but stubs the processing (services/batch_service/local_processor.py:192-203
simulates work); here processing is real since the router can route.
stdlib sqlite3 run in a thread executor — the write rate is a handful of
status updates per job, not worth an async driver."""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
import uuid

from aiohttp import web

from ..utils.jsonio import loads_off_loop
from ..utils.logging import init_logger

logger = init_logger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS batches (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    input_file_id TEXT NOT NULL,
    endpoint TEXT NOT NULL,
    completion_window TEXT,
    created_at INTEGER,
    started_at INTEGER,
    completed_at INTEGER,
    output_file_id TEXT,
    error TEXT,
    user TEXT,
    counts TEXT DEFAULT '{}'
)
"""


class BatchService:
    def __init__(self, db_path: str, state):
        self.db_path = db_path
        self.state = state
        self._task: asyncio.Task | None = None

    # -- db helpers (sync, called via to_thread) ---------------------------

    def _db(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.db_path)
        conn.row_factory = sqlite3.Row
        conn.execute(_SCHEMA)
        return conn

    def _insert(self, row: dict) -> None:
        with self._db() as conn:
            conn.execute(
                "INSERT INTO batches (id,status,input_file_id,endpoint,"
                "completion_window,created_at,user) VALUES (?,?,?,?,?,?,?)",
                (
                    row["id"],
                    row["status"],
                    row["input_file_id"],
                    row["endpoint"],
                    row["completion_window"],
                    row["created_at"],
                    row["user"],
                ),
            )

    def _update(self, batch_id: str, **fields) -> None:
        sets = ", ".join(f"{k}=?" for k in fields)
        with self._db() as conn:
            conn.execute(
                f"UPDATE batches SET {sets} WHERE id=?",
                (*fields.values(), batch_id),
            )

    def _get(self, batch_id: str) -> dict | None:
        with self._db() as conn:
            row = conn.execute(
                "SELECT * FROM batches WHERE id=?", (batch_id,)
            ).fetchone()
        return dict(row) if row else None

    def _list(self, user: str) -> list[dict]:
        with self._db() as conn:
            rows = conn.execute(
                "SELECT * FROM batches WHERE user=? ORDER BY created_at", (user,)
            ).fetchall()
        return [dict(r) for r in rows]

    def _next_pending(self) -> dict | None:
        with self._db() as conn:
            row = conn.execute(
                "SELECT * FROM batches WHERE status='validating' "
                "ORDER BY created_at LIMIT 1"
            ).fetchone()
        return dict(row) if row else None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._task = asyncio.create_task(self._worker())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _worker(self) -> None:
        while True:
            try:
                job = await asyncio.to_thread(self._next_pending)
                if job is None:
                    await asyncio.sleep(2.0)
                    continue
                await self._process(job)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning("batch worker error: %s", e)
                await asyncio.sleep(2.0)

    async def _process(self, job: dict) -> None:
        batch_id = job["id"]
        await asyncio.to_thread(
            self._update, batch_id, status="in_progress", started_at=int(time.time())
        )
        files = self.state.files
        content = files.get_content(job["user"], job["input_file_id"])
        if content is None:
            await asyncio.to_thread(
                self._update, batch_id, status="failed", error="input file not found"
            )
            return
        out_lines, ok, failed = [], 0, 0
        for line in content.decode().splitlines():
            if not line.strip():
                continue
            item: dict | None = None
            try:
                # a batch line is one full OpenAI request body — parse
                # large ones off the loop like the live request path
                parsed = await loads_off_loop(line)
                item = parsed if isinstance(parsed, dict) else None
                if item is None:
                    raise ValueError("batch line is not a JSON object")
                resp = await self._run_one(item, job["endpoint"])
                out_lines.append(json.dumps(resp))
                ok += 1
            except Exception as e:
                failed += 1
                out_lines.append(
                    json.dumps(
                        {
                            "custom_id": item.get("custom_id") if item else None,
                            "error": {"message": str(e)},
                        }
                    )
                )
        out_meta = files.save(
            job["user"], f"{batch_id}_output.jsonl",
            "\n".join(out_lines).encode(), "batch_output",
        )
        await asyncio.to_thread(
            self._update,
            batch_id,
            status="completed",
            completed_at=int(time.time()),
            output_file_id=out_meta["id"],
            counts=json.dumps({"total": ok + failed, "completed": ok, "failed": failed}),
        )
        logger.info("batch %s finished: %d ok, %d failed", batch_id, ok, failed)

    async def _run_one(self, item: dict, endpoint: str) -> dict:
        """Execute one batch line through an engine chosen by the router's
        policy (a thin internal client — no HTTP hop through ourselves)."""
        from .routing import RoutingContext

        body = item.get("body", {})
        svc = self.state.request_service
        model = svc.resolve_alias(body.get("model"))
        eps = svc._eligible_endpoints(model)
        if not eps:
            raise RuntimeError(f"no engine for model {model!r}")
        ctx = RoutingContext(
            endpoints=eps,
            request_stats=self.state.request_monitor.get_request_stats(),
            body=body,
        )
        url = await self.state.policy.route(ctx)
        async with svc.session.post(url + endpoint, json=body) as resp:
            payload = await resp.json()
        return {
            "id": f"batch_req_{uuid.uuid4().hex[:12]}",
            "custom_id": item.get("custom_id"),
            "response": {"status_code": resp.status, "body": payload},
        }

    # -- routes ------------------------------------------------------------

    def register_routes(self, app: web.Application) -> None:
        app.router.add_post("/v1/batches", self.h_create)
        app.router.add_get("/v1/batches", self.h_list)
        app.router.add_get("/v1/batches/{batch_id}", self.h_get)
        app.router.add_post("/v1/batches/{batch_id}/cancel", self.h_cancel)

    @staticmethod
    def _user(request: web.Request) -> str:
        return request.headers.get("X-User-Id", "anonymous")

    @staticmethod
    def _card(row: dict) -> dict:
        return {
            "id": row["id"],
            "object": "batch",
            "endpoint": row["endpoint"],
            "input_file_id": row["input_file_id"],
            "completion_window": row["completion_window"],
            "status": row["status"],
            "created_at": row["created_at"],
            "in_progress_at": row["started_at"],
            "completed_at": row["completed_at"],
            "output_file_id": row["output_file_id"],
            "request_counts": json.loads(row["counts"] or "{}"),
            "errors": row["error"],
        }

    async def h_create(self, request: web.Request) -> web.Response:
        body = await request.json()
        for field in ("input_file_id", "endpoint"):
            if field not in body:
                return web.json_response(
                    {"error": {"message": f"missing {field}"}}, status=400
                )
        row = {
            "id": f"batch_{uuid.uuid4().hex[:24]}",
            "status": "validating",
            "input_file_id": body["input_file_id"],
            "endpoint": body["endpoint"],
            "completion_window": body.get("completion_window", "24h"),
            "created_at": int(time.time()),
            "user": self._user(request),
        }
        await asyncio.to_thread(self._insert, row)
        stored = await asyncio.to_thread(self._get, row["id"])
        return web.json_response(self._card(stored))

    async def h_list(self, request: web.Request) -> web.Response:
        rows = await asyncio.to_thread(self._list, self._user(request))
        return web.json_response(
            {"object": "list", "data": [self._card(r) for r in rows]}
        )

    async def h_get(self, request: web.Request) -> web.Response:
        row = await asyncio.to_thread(self._get, request.match_info["batch_id"])
        if row is None:
            return web.json_response(
                {"error": {"message": "batch not found"}}, status=404
            )
        return web.json_response(self._card(row))

    async def h_cancel(self, request: web.Request) -> web.Response:
        batch_id = request.match_info["batch_id"]
        row = await asyncio.to_thread(self._get, batch_id)
        if row is None:
            return web.json_response(
                {"error": {"message": "batch not found"}}, status=404
            )
        if row["status"] in ("validating", "in_progress"):
            await asyncio.to_thread(self._update, batch_id, status="cancelled")
            row = await asyncio.to_thread(self._get, batch_id)
        return web.json_response(self._card(row))
