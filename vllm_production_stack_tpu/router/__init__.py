"""OpenAI-compatible request router for the TPU serving stack.

The reference stack's core artifact is its router (src/vllm_router/): an
OpenAI-compatible proxy that discovers serving-engine pods, tracks their load,
and routes each request with pluggable algorithms (app.py:83-300,
routers/routing_logic.py:50-527). This package is the TPU stack's router:
same capabilities, rebuilt on aiohttp with explicit state wiring (one
`RouterState` object owned by the app) instead of singleton registries, and
speaking the `tpu:*` engine metrics contract (metrics_contract.py) instead of
`vllm:*`.
"""

from .discovery import Endpoint, ModelInfo, ServiceDiscovery, StaticDiscovery
from .routing import RoutingContext, RoutingPolicy, make_policy

__all__ = [
    "Endpoint",
    "ModelInfo",
    "ServiceDiscovery",
    "StaticDiscovery",
    "RoutingContext",
    "RoutingPolicy",
    "make_policy",
]
