"""Kubernetes-style feature gates.

`--feature-gates SemanticCache=true,PIIDetection=false` toggles optional
subsystems; each gate has a maturity stage with a default (reference
experimental/feature_gates.py:16-109). Parsed once at startup into a plain
object on the app state — no singleton."""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Stage(enum.Enum):
    ALPHA = "alpha"  # default off
    BETA = "beta"  # default on
    GA = "ga"  # always on


@dataclass(frozen=True)
class GateSpec:
    name: str
    stage: Stage
    description: str


KNOWN_GATES = {
    g.name: g
    for g in (
        GateSpec("SemanticCache", Stage.ALPHA, "semantic response cache"),
        GateSpec("PIIDetection", Stage.ALPHA, "PII request screening"),
    )
}


class FeatureGates:
    def __init__(self, spec: str = ""):
        self._enabled: dict[str, bool] = {
            name: g.stage is not Stage.ALPHA for name, g in KNOWN_GATES.items()
        }
        for part in filter(None, (p.strip() for p in spec.split(","))):
            name, _, value = part.partition("=")
            if name not in KNOWN_GATES:
                raise ValueError(
                    f"unknown feature gate {name!r}; known: {sorted(KNOWN_GATES)}"
                )
            if KNOWN_GATES[name].stage is Stage.GA and value.lower() == "false":
                raise ValueError(f"GA feature gate {name!r} cannot be disabled")
            self._enabled[name] = value.lower() in ("true", "1", "yes")

    def enabled(self, name: str) -> bool:
        return self._enabled.get(name, False)

    def as_dict(self) -> dict[str, bool]:
        return dict(self._enabled)
