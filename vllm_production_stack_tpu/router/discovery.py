"""Serving-engine discovery for the router.

The reference tracks engine endpoints three ways (service_discovery.py:206-1176):
a static URL list with optional health probes, a Kubernetes pod-IP watch, and a
Kubernetes service watch. Same trio here. The Kubernetes modes talk to the API
server directly over aiohttp streaming watches (the `kubernetes` client package
is not a dependency); in-cluster credentials come from the standard service
account mount.

Discovery is the single source of truth for (a) which engines exist, (b) which
models each serves (scraped from the engine's /v1/models), and (c) whether an
engine is sleeping — routing filters on all three.
"""

from __future__ import annotations

import asyncio
import json
import ssl
import time
import uuid
from dataclasses import dataclass, field

import aiohttp

from ..utils.logging import init_logger

logger = init_logger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class ModelInfo:
    """One entry of an engine's /v1/models listing. `parent` set means a LoRA
    adapter derived from a base model (the reference's adapter convention,
    service_discovery.py:42-77)."""

    id: str
    created: int = 0
    owned_by: str = "tpu-stack"
    root: str | None = None
    parent: str | None = None

    @property
    def is_adapter(self) -> bool:
        return self.parent is not None

    @classmethod
    def from_dict(cls, d: dict) -> "ModelInfo":
        return cls(
            id=d.get("id", ""),
            created=d.get("created", 0),
            owned_by=d.get("owned_by", "tpu-stack"),
            root=d.get("root"),
            parent=d.get("parent"),
        )


@dataclass
class Endpoint:
    """A live serving engine the router can proxy to."""

    url: str
    model_names: list[str] = field(default_factory=list)
    endpoint_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    model_label: str = ""
    added_at: float = field(default_factory=time.time)
    sleeping: bool = False
    healthy: bool = True
    pod_name: str | None = None
    namespace: str | None = None
    model_info: dict[str, ModelInfo] = field(default_factory=dict)

    def has_model(self, model: str) -> bool:
        return model in self.model_names

    def base_models(self) -> list[str]:
        return [m for m, i in self.model_info.items() if not i.parent]

    def adapters(self) -> list[str]:
        return [m for m, i in self.model_info.items() if i.parent]

    def to_dict(self) -> dict:
        return {
            "url": self.url,
            "model_names": self.model_names,
            "endpoint_id": self.endpoint_id,
            "model_label": self.model_label,
            "added_at": self.added_at,
            "sleeping": self.sleeping,
            "healthy": self.healthy,
            "pod_name": self.pod_name,
            "namespace": self.namespace,
        }


class ServiceDiscovery:
    """Base: maintains the endpoint snapshot the hot path reads.

    `endpoints()` must be cheap and non-blocking — it is called on every
    request (reference request.py:207-208 takes a lock-guarded copy; here the
    snapshot is an immutable list swapped atomically, so readers need no lock).
    """

    def __init__(self) -> None:
        self._snapshot: list[Endpoint] = []
        self._listeners: list = []

    def endpoints(self) -> list[Endpoint]:
        return self._snapshot

    def add_listener(self, cb) -> None:
        """Subscribe to endpoint churn: cb(removed_urls: set, current_urls:
        set), called synchronously on every publish whose URL set changed.
        This is how endpoint death reaches per-endpoint routing state (the
        prefix trie, the session ring, the embedded KV index) — without it
        a drained pod lingered in the trie as a routing candidate forever."""
        self._listeners.append(cb)

    def _publish(self, eps: list[Endpoint]) -> None:
        old_urls = {e.url for e in self._snapshot}
        self._snapshot = list(eps)
        new_urls = {e.url for e in self._snapshot}
        if old_urls == new_urls or not self._listeners:
            return
        removed = old_urls - new_urls
        for cb in list(self._listeners):
            try:
                cb(removed, new_urls)
            except Exception:  # a listener fault must not kill the watcher
                logger.exception("endpoint-churn listener failed")

    async def start(self) -> None:  # pragma: no cover - overridden
        pass

    async def stop(self) -> None:  # pragma: no cover - overridden
        pass

    def is_healthy(self) -> bool:
        return True

    def set_sleeping(self, url: str, sleeping: bool) -> None:
        """Record an engine's sleep state so routing can skip it (the
        reference labels the pod instead, service_discovery.py:414-496; the
        router-side flag covers static mode too)."""
        for ep in self._snapshot:
            if ep.url == url:
                ep.sleeping = sleeping


class StaticDiscovery(ServiceDiscovery):
    """Fixed URL list, with an optional async health/model prober.

    Mirrors the reference's StaticServiceDiscovery behavior
    (service_discovery.py:206-341): when probing is on, each engine's
    /v1/models is scraped on an interval; engines that fail the probe drop out
    of the snapshot until they recover.
    """

    def __init__(
        self,
        urls: list[str],
        models: list[list[str]] | None = None,
        model_labels: list[str] | None = None,
        probe_interval: float | None = None,
    ):
        super().__init__()
        self.urls = urls
        self.static_models = models
        self.probe_interval = probe_interval
        labels = model_labels or [""] * len(urls)
        self._endpoints = [
            Endpoint(
                url=u,
                model_names=list(models[i]) if models else [],
                model_label=labels[i] if i < len(labels) else "",
            )
            for i, u in enumerate(urls)
        ]
        self._publish(self._endpoints)
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        if self.probe_interval:
            self._task = asyncio.create_task(self._probe_loop())
        elif not self.static_models:
            # one-shot best-effort model scrape so /v1/models isn't empty
            await self._probe_once()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _probe_loop(self) -> None:
        while True:
            try:
                await self._probe_once()
            except Exception as e:  # keep probing through transient faults
                logger.warning("health probe pass failed: %s", e)
            await asyncio.sleep(self.probe_interval)

    async def _probe_once(self) -> None:
        timeout = aiohttp.ClientTimeout(total=5)
        async with aiohttp.ClientSession(timeout=timeout) as sess:
            results = await asyncio.gather(
                *(self._probe_endpoint(sess, ep) for ep in self._endpoints)
            )
        self._publish([ep for ep, ok in zip(self._endpoints, results) if ok])

    async def _probe_endpoint(
        self, sess: aiohttp.ClientSession, ep: Endpoint
    ) -> bool:
        try:
            async with sess.get(ep.url + "/v1/models") as resp:
                if resp.status != 200:
                    ep.healthy = False
                    return False
                data = await resp.json()
            ep.model_info = {
                m["id"]: ModelInfo.from_dict(m) for m in data.get("data", [])
            }
            scraped = list(ep.model_info)
            if self.static_models:
                # static model list is authoritative; probe only gates health
                if not ep.model_names:
                    ep.model_names = scraped
            else:
                ep.model_names = scraped
            ep.healthy = True
            return True
        except Exception:
            ep.healthy = False
            return False


class KubernetesDiscovery(ServiceDiscovery):
    """Watches pods (or services) matching a label selector via the API
    server's streaming watch, scraping each ready pod's /v1/models.

    The reference does the same through the kubernetes client in a daemon
    thread (service_discovery.py:344-759); here it's an asyncio task speaking
    the watch protocol directly. Ready pods with a `model` label become
    endpoints; pods labeled `sleeping=true` stay listed but are filtered by
    routing; deleted/unready pods drop out.
    """

    def __init__(
        self,
        namespace: str = "default",
        label_selector: str = "",
        port: int = 8000,
        mode: str = "pod",  # "pod" (pod IPs) or "service" (service DNS)
        api_server: str | None = None,
        token: str | None = None,
        rescrape_interval: float = 30.0,
    ):
        super().__init__()
        self.namespace = namespace
        self.label_selector = label_selector
        self.port = port
        self.mode = mode
        self.rescrape_interval = rescrape_interval
        self._api_server = api_server
        self._token = token
        self._ssl: ssl.SSLContext | bool = False
        self._eps: dict[str, Endpoint] = {}  # pod/service name -> endpoint
        self._task: asyncio.Task | None = None
        self._watch_alive = False

    # -- credentials -------------------------------------------------------

    def _load_in_cluster(self) -> None:
        if self._api_server is None:
            import os

            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            self._api_server = f"https://{host}:{port}"
            try:
                with open(f"{SA_DIR}/token") as f:
                    self._token = f.read().strip()
                ctx = ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
                self._ssl = ctx
            except FileNotFoundError:
                logger.warning("no in-cluster service account credentials found")

    @property
    def _headers(self) -> dict[str, str]:
        return {"Authorization": f"Bearer {self._token}"} if self._token else {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._load_in_cluster()
        self._task = asyncio.create_task(self._watch_loop())
        self._rescrape_task = asyncio.create_task(self._rescrape_loop())

    async def stop(self) -> None:
        for task in (self._task, getattr(self, "_rescrape_task", None)):
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

    async def _rescrape_loop(self) -> None:
        """Periodically refresh each endpoint's model list: pods load LoRA
        adapters (and finish model loads) without emitting pod events, so the
        watch alone would serve a stale /v1/models forever."""
        while True:
            await asyncio.sleep(self.rescrape_interval)
            try:
                async with aiohttp.ClientSession(headers=self._headers) as sess:
                    for name, ep in list(self._eps.items()):
                        models = await self._scrape_models(sess, ep.url)
                        if models is not None:
                            ep.model_info = models
                            ep.model_names = list(models)
                self._publish(list(self._eps.values()))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning("model rescrape pass failed: %s", e)

    def is_healthy(self) -> bool:
        return self._watch_alive

    # -- watch -------------------------------------------------------------

    def _watch_url(self, watch: bool) -> str:
        kind = "pods" if self.mode == "pod" else "services"
        url = f"{self._api_server}/api/v1/namespaces/{self.namespace}/{kind}"
        sel = f"labelSelector={self.label_selector}" if self.label_selector else ""
        q = "&".join(x for x in (sel, "watch=true" if watch else "") if x)
        return f"{url}?{q}" if q else url

    async def _watch_loop(self) -> None:
        while True:
            try:
                async with aiohttp.ClientSession(headers=self._headers) as sess:
                    # initial list, then watch for deltas
                    async with sess.get(self._watch_url(False), ssl=self._ssl) as r:
                        data = await r.json()
                    for item in data.get("items", []):
                        await self._on_event(sess, "ADDED", item)
                    self._watch_alive = True
                    timeout = aiohttp.ClientTimeout(total=None, sock_read=300)
                    async with sess.get(
                        self._watch_url(True), ssl=self._ssl, timeout=timeout
                    ) as resp:
                        async for line in resp.content:
                            if not line.strip():
                                continue
                            # tpulint: allow(async-blocking) — one watch
                            # event per line, KB-scale by apiserver
                            # construction
                            ev = json.loads(line)
                            await self._on_event(
                                sess, ev.get("type", ""), ev.get("object", {})
                            )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self._watch_alive = False
                logger.warning("k8s watch interrupted, retrying: %s", e)
                await asyncio.sleep(2.0)

    async def _on_event(
        self, sess: aiohttp.ClientSession, ev_type: str, obj: dict
    ) -> None:
        meta = obj.get("metadata", {})
        name = meta.get("name", "")
        if not name:
            return
        if ev_type == "DELETED" or meta.get("deletionTimestamp"):
            self._eps.pop(name, None)
            self._publish(list(self._eps.values()))
            return
        labels = meta.get("labels", {}) or {}
        if self.mode == "pod":
            status = obj.get("status", {})
            ready = any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in status.get("conditions", [])
            )
            ip = status.get("podIP")
            if not ready or not ip:
                self._eps.pop(name, None)
                self._publish(list(self._eps.values()))
                return
            url = f"http://{ip}:{self.port}"
        else:
            url = f"http://{name}.{self.namespace}.svc:{self.port}"

        ep = self._eps.get(name)
        if ep is None or ep.url != url:
            ep = Endpoint(url=url, pod_name=name, namespace=self.namespace)
            models = await self._scrape_models(sess, url)
            if models is None:
                return  # not serving yet; next MODIFIED event retries
            ep.model_info = models
            ep.model_names = list(models)
        ep.model_label = labels.get("model", ep.model_label)
        ep.sleeping = labels.get("sleeping", "") == "true"
        self._eps[name] = ep
        self._publish(list(self._eps.values()))

    async def _scrape_models(
        self, sess: aiohttp.ClientSession, url: str
    ) -> dict[str, ModelInfo] | None:
        try:
            async with sess.get(
                url + "/v1/models", timeout=aiohttp.ClientTimeout(total=5)
            ) as resp:
                if resp.status != 200:
                    return None
                data = await resp.json()
            return {m["id"]: ModelInfo.from_dict(m) for m in data.get("data", [])}
        except Exception:
            return None


def make_discovery(kind: str, **kw) -> ServiceDiscovery:
    if kind == "static":
        return StaticDiscovery(
            urls=kw["urls"],
            models=kw.get("models"),
            model_labels=kw.get("model_labels"),
            probe_interval=kw.get("probe_interval"),
        )
    if kind in ("k8s", "k8s_pod_ip"):
        return KubernetesDiscovery(mode="pod", **kw.get("k8s", {}))
    if kind in ("k8s_service", "k8s_service_name"):
        return KubernetesDiscovery(mode="service", **kw.get("k8s", {}))
    raise ValueError(f"unknown service discovery kind: {kind}")
