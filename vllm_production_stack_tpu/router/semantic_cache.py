"""Semantic response cache (feature gate: SemanticCache).

Embeds the chat messages and serves a cached completion when a previous
request is similar enough (inner product >= threshold). The reference uses
sentence-transformers + FAISS (experimental/semantic_cache/:16-353); here the
index is plain numpy — at router cache sizes (thousands of entries) a matmul
against the normalized embedding matrix beats carrying a native ANN
dependency. The embedder is pluggable so tests inject a deterministic one."""

from __future__ import annotations

import json
import time

import numpy as np
from aiohttp import web

from ..utils.jsonio import loads_off_loop
from ..utils.logging import init_logger

logger = init_logger(__name__)


class NumpyIndex:
    """Exact inner-product search over normalized vectors."""

    def __init__(self, dim: int):
        self.dim = dim
        self._vecs = np.zeros((0, dim), dtype=np.float32)
        self._payloads: list[dict] = []

    def __len__(self) -> int:
        return len(self._payloads)

    def add(self, vec: np.ndarray, payload: dict) -> None:
        vec = vec.astype(np.float32).reshape(1, -1)
        vec /= np.linalg.norm(vec) + 1e-9
        self._vecs = np.concatenate([self._vecs, vec])
        self._payloads.append(payload)

    def search(self, vec: np.ndarray) -> tuple[float, dict | None]:
        if not self._payloads:
            return 0.0, None
        q = vec.astype(np.float32).ravel()
        q /= np.linalg.norm(q) + 1e-9
        sims = self._vecs @ q
        best = int(np.argmax(sims))
        return float(sims[best]), self._payloads[best]


class HashingEmbedder:
    """Dependency-free fallback embedder: token-hash bag-of-words. Real
    deployments pass a sentence-transformers dir; tests and air-gapped runs
    still get exact-duplicate hits from this."""

    def __init__(self, dim: int = 512):
        self.dim = dim

    def encode(self, text: str) -> np.ndarray:
        import xxhash

        v = np.zeros(self.dim, dtype=np.float32)
        for tok in text.lower().split():
            v[xxhash.xxh64_intdigest(tok) % self.dim] += 1.0
        return v


class EngineEmbedder:
    """REAL semantic vectors with zero extra dependencies: embed through a
    serving engine's /v1/embeddings (last-token-pooled hidden states,
    engine/server.py). The reference needs sentence-transformers + FAISS in
    the router image for this; the TPU stack's engines ARE an embedding
    service, so `--semantic-cache-dir engine` borrows the model itself.
    Costs one engine round trip per lookup/store — gate-enabled operators
    are trading a little engine time for completion-cache hits."""

    def __init__(self, state):
        self.state = state

    async def encode_async(self, text: str, model: str | None) -> np.ndarray:
        eps = [
            e for e in self.state.discovery.endpoints()
            if e.healthy and not e.sleeping
            and (not model or e.has_model(model) or not e.model_names)
        ]
        if not eps:
            raise LookupError("no engine available to embed")
        if not model:
            if not eps[0].model_names:
                # scrape window: model list not known yet — clean miss, not
                # an IndexError masquerading as an embed failure
                raise LookupError("no model name known yet for embedding")
            model = eps[0].model_names[0]
        import aiohttp

        session = self.state.request_service.session
        async with session.post(
            eps[0].url + "/v1/embeddings",
            # bound the embed cost; the TAIL carries the newest turns,
            # which dominate similarity for conversation caching
            json={"model": model, "input": text[-4000:]},
            timeout=aiohttp.ClientTimeout(total=10),
        ) as resp:
            if resp.status != 200:
                raise LookupError(f"embedding backend returned {resp.status}")
            data = await resp.json()
        return np.asarray(data["data"][0]["embedding"], dtype=np.float32)


def _load_embedder(model_dir: str, state=None):
    if model_dir in ("hashing", "builtin"):
        return HashingEmbedder()
    if model_dir == "engine":
        if state is None:
            raise ValueError(
                "--semantic-cache-dir engine needs the router state"
            )
        return EngineEmbedder(state)
    try:
        from sentence_transformers import SentenceTransformer

        m = SentenceTransformer(model_dir)
        return m
    except Exception as e:
        logger.warning(
            "falling back to hashing embedder (%s unusable: %s)", model_dir, e
        )
        return HashingEmbedder()


class SemanticCache:
    def __init__(
        self, model_dir: str, threshold: float = 0.9, embedder=None,
        state=None,
    ):
        self.threshold = threshold
        self.embedder = embedder or _load_embedder(model_dir, state=state)
        # index dimension discovered from the first vector (async embedders
        # can't be probed at construction time)
        self.index: NumpyIndex | None = None
        if not hasattr(self.embedder, "encode_async"):
            probe = np.asarray(
                self.embedder.encode("probe"), dtype=np.float32
            )
            self.index = NumpyIndex(probe.ravel().shape[0])
        self.hits = 0
        self.lookups = 0
        self._recent: dict = {}  # (model, text) -> vec, bounded at 64

    async def _encode(self, text: str, model: str | None) -> np.ndarray:
        # miss-path memo: a cache miss embeds in lookup() and would embed
        # the SAME text again in store() — with the engine embedder that
        # is a second full round trip per uncached request
        key = (model, text)
        cached = self._recent.get(key)
        if cached is not None:
            return cached
        if hasattr(self.embedder, "encode_async"):
            vec = await self.embedder.encode_async(text, model)
        else:
            vec = np.asarray(self.embedder.encode(text))
        self._recent[key] = vec
        while len(self._recent) > 64:
            self._recent.pop(next(iter(self._recent)))
        return vec

    def _ensure_index(self, vec: np.ndarray) -> bool:
        """Returns False when the vector cannot enter this index (dimension
        mismatch — e.g. a multi-model fleet where models have different
        hidden sizes); callers treat that as a miss, never an error."""
        if self.index is None:
            self.index = NumpyIndex(vec.ravel().shape[0])
        if vec.ravel().shape[0] != self.index.dim:
            logger.warning(
                "semantic-cache embedding dim %d != index dim %d; "
                "skipping (multi-model fleet?)",
                vec.ravel().shape[0], self.index.dim,
            )
            return False
        return True

    @staticmethod
    def _text_of(body: dict) -> str:
        msgs = body.get("messages", [])
        return "\n".join(
            f"{m.get('role', '')}: {m.get('content', '')}"
            for m in msgs
            if isinstance(m.get("content"), str)
        )

    async def lookup(self, request: web.Request):
        """Returns a cached Response or None. Streaming requests skip the
        cache (a cached body can't replay a stream faithfully)."""
        raw = await request.read()
        try:
            body = await loads_off_loop(raw)
        except json.JSONDecodeError:
            return None
        if body.get("stream"):
            return None
        self.lookups += 1
        try:
            vec = await self._encode(self._text_of(body), body.get("model"))
        except Exception as e:  # embed backend down => cache miss, not 500
            logger.warning("semantic-cache embed failed on lookup: %s", e)
            return None
        if not self._ensure_index(vec):
            return None
        sim, payload = self.index.search(vec)
        if payload is None or sim < self.threshold:
            return None
        if payload.get("model") != body.get("model"):
            return None
        self.hits += 1
        cached = dict(payload["response"])
        cached["cached"] = True
        cached["similarity"] = round(sim, 4)
        return web.json_response(cached)

    async def store(self, body: dict, response: dict) -> None:
        try:
            vec = await self._encode(self._text_of(body), body.get("model"))
        except Exception as e:  # embed backend down => skip caching
            logger.warning("semantic-cache embed failed on store: %s", e)
            return
        if not self._ensure_index(vec):
            return
        self.index.add(
            vec,
            {
                "model": body.get("model"),
                "response": response,
                "stored_at": time.time(),
            },
        )
