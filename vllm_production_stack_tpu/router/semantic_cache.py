"""Semantic response cache (feature gate: SemanticCache).

Embeds the chat messages and serves a cached completion when a previous
request is similar enough (inner product >= threshold). The reference uses
sentence-transformers + FAISS (experimental/semantic_cache/:16-353); here the
index is plain numpy — at router cache sizes (thousands of entries) a matmul
against the normalized embedding matrix beats carrying a native ANN
dependency. The embedder is pluggable so tests inject a deterministic one."""

from __future__ import annotations

import json
import time

import numpy as np
from aiohttp import web

from ..utils.logging import init_logger

logger = init_logger(__name__)


class NumpyIndex:
    """Exact inner-product search over normalized vectors."""

    def __init__(self, dim: int):
        self.dim = dim
        self._vecs = np.zeros((0, dim), dtype=np.float32)
        self._payloads: list[dict] = []

    def __len__(self) -> int:
        return len(self._payloads)

    def add(self, vec: np.ndarray, payload: dict) -> None:
        vec = vec.astype(np.float32).reshape(1, -1)
        vec /= np.linalg.norm(vec) + 1e-9
        self._vecs = np.concatenate([self._vecs, vec])
        self._payloads.append(payload)

    def search(self, vec: np.ndarray) -> tuple[float, dict | None]:
        if not self._payloads:
            return 0.0, None
        q = vec.astype(np.float32).ravel()
        q /= np.linalg.norm(q) + 1e-9
        sims = self._vecs @ q
        best = int(np.argmax(sims))
        return float(sims[best]), self._payloads[best]


class HashingEmbedder:
    """Dependency-free fallback embedder: token-hash bag-of-words. Real
    deployments pass a sentence-transformers dir; tests and air-gapped runs
    still get exact-duplicate hits from this."""

    def __init__(self, dim: int = 512):
        self.dim = dim

    def encode(self, text: str) -> np.ndarray:
        import xxhash

        v = np.zeros(self.dim, dtype=np.float32)
        for tok in text.lower().split():
            v[xxhash.xxh64_intdigest(tok) % self.dim] += 1.0
        return v


def _load_embedder(model_dir: str):
    if model_dir in ("hashing", "builtin"):
        return HashingEmbedder()
    try:
        from sentence_transformers import SentenceTransformer

        m = SentenceTransformer(model_dir)
        return m
    except Exception as e:
        logger.warning(
            "falling back to hashing embedder (%s unusable: %s)", model_dir, e
        )
        return HashingEmbedder()


class SemanticCache:
    def __init__(self, model_dir: str, threshold: float = 0.9, embedder=None):
        self.threshold = threshold
        self.embedder = embedder or _load_embedder(model_dir)
        probe = np.asarray(self.embedder.encode("probe"), dtype=np.float32)
        self.index = NumpyIndex(probe.ravel().shape[0])
        self.hits = 0
        self.lookups = 0

    @staticmethod
    def _text_of(body: dict) -> str:
        msgs = body.get("messages", [])
        return "\n".join(
            f"{m.get('role', '')}: {m.get('content', '')}"
            for m in msgs
            if isinstance(m.get("content"), str)
        )

    async def lookup(self, request: web.Request):
        """Returns a cached Response or None. Streaming requests skip the
        cache (a cached body can't replay a stream faithfully)."""
        raw = await request.read()
        try:
            body = json.loads(raw)
        except json.JSONDecodeError:
            return None
        if body.get("stream"):
            return None
        self.lookups += 1
        vec = np.asarray(self.embedder.encode(self._text_of(body)))
        sim, payload = self.index.search(vec)
        if payload is None or sim < self.threshold:
            return None
        if payload.get("model") != body.get("model"):
            return None
        self.hits += 1
        cached = dict(payload["response"])
        cached["cached"] = True
        cached["similarity"] = round(sim, 4)
        return web.json_response(cached)

    def store(self, body: dict, response: dict) -> None:
        vec = np.asarray(self.embedder.encode(self._text_of(body)))
        self.index.add(
            vec,
            {
                "model": body.get("model"),
                "response": response,
                "stored_at": time.time(),
            },
        )
