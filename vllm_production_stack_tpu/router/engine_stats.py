"""Engine /metrics scraper.

Polls every discovered engine's Prometheus endpoint and parses the `tpu:*`
serving metrics into an `EngineStats` snapshot per URL. This is the TPU
counterpart of the reference's EngineStatsScraper, which parses `vllm:*`
names (stats/engine_stats.py:42-218); the names here come from
metrics_contract.py so engine exporter and router scraper can't drift.
Runs as an asyncio task (the reference uses a daemon thread)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

import aiohttp
from prometheus_client.parser import text_string_to_metric_families

from .. import metrics_contract as mc
from ..utils.logging import init_logger

logger = init_logger(__name__)


@dataclass
class EngineStats:
    num_running_requests: float = 0
    num_queuing_requests: float = 0
    hbm_kv_usage_perc: float = 0.0
    prefix_cache_hit_rate: float = 0.0
    prefix_cache_hits_total: float = 0
    prefix_cache_queries_total: float = 0
    # peer-engine KV tier (docs/35-peer-kv-reuse.md): the two numbers the
    # priced route-vs-migrate scoring needs per engine — this engine's
    # measured peer-fetch bandwidth (tpu:kv_tier_bandwidth_bytes_per_s
    # {tier="peer",direction="in"}; the exporter renders 0.0 until the
    # TierBandwidth sample floor is crossed, so nonzero here really means
    # MEASURED and scoring below it keeps owner affinity / the
    # exploration rule) and its analytic KV bytes per token
    kv_peer_bw_in_bytes_per_s: float = 0.0
    # device-path peer pulls (docs/39-device-peer-kv.md): measured ICI/DCN
    # collective bandwidth {tier="device",direction="in"} — once nonzero
    # the migrate pricing uses max(peer, device), so the scoring shifts
    # toward migration automatically as the faster link gets measured
    kv_device_bw_in_bytes_per_s: float = 0.0
    kv_bytes_per_token: float = 0.0

    _FIELDS = {
        mc.NUM_REQUESTS_RUNNING: "num_running_requests",
        mc.NUM_REQUESTS_WAITING: "num_queuing_requests",
        mc.HBM_KV_USAGE_PERC: "hbm_kv_usage_perc",
        mc.PREFIX_CACHE_HIT_RATE: "prefix_cache_hit_rate",
        mc.PREFIX_CACHE_HITS: "prefix_cache_hits_total",
        mc.PREFIX_CACHE_QUERIES: "prefix_cache_queries_total",
        mc.KV_BYTES_PER_TOKEN: "kv_bytes_per_token",
    }

    @property
    def load(self) -> float:
        """Seat pressure the migrate scoring compares engines on:
        running + queued requests."""
        return self.num_running_requests + self.num_queuing_requests

    @classmethod
    def from_scrape(cls, text: str) -> "EngineStats":
        stats = cls()
        for family in text_string_to_metric_families(text):
            for sample in family.samples:
                # counters' samples keep the _total suffix the family drops
                field = cls._FIELDS.get(sample.name)
                if field is not None:
                    setattr(stats, field, sample.value)
                elif (
                    sample.name == mc.KV_TIER_BANDWIDTH
                    and sample.labels.get("direction") == "in"
                ):
                    tier = sample.labels.get("tier")
                    if tier == "peer":
                        stats.kv_peer_bw_in_bytes_per_s = sample.value
                    elif tier == "device":
                        stats.kv_device_bw_in_bytes_per_s = sample.value
        return stats


class EngineStatsScraper:
    def __init__(self, discovery, interval: float = 10.0):
        self.discovery = discovery
        self.interval = interval
        self._stats: dict[str, EngineStats] = {}
        self._task: asyncio.Task | None = None

    def get_engine_stats(self) -> dict[str, EngineStats]:
        return dict(self._stats)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def is_healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _loop(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except Exception as e:
                logger.warning("engine stats scrape failed: %s", e)
            await asyncio.sleep(self.interval)

    async def scrape_once(self) -> None:
        eps = self.discovery.endpoints()
        timeout = aiohttp.ClientTimeout(total=5)
        async with aiohttp.ClientSession(timeout=timeout) as sess:
            results = await asyncio.gather(
                *(self._scrape(sess, ep.url) for ep in eps)
            )
        fresh = {url: s for url, s in results if s is not None}
        # keep only live endpoints so dead engines don't pin stale stats
        self._stats = fresh

    async def _scrape(self, sess, url: str):
        try:
            async with sess.get(url + "/metrics") as resp:
                if resp.status != 200:
                    return url, None
                return url, EngineStats.from_scrape(await resp.text())
        except Exception:
            return url, None
