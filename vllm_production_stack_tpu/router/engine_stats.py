"""Engine /metrics scraper.

Polls every discovered engine's Prometheus endpoint and parses the `tpu:*`
serving metrics into an `EngineStats` snapshot per URL. This is the TPU
counterpart of the reference's EngineStatsScraper, which parses `vllm:*`
names (stats/engine_stats.py:42-218); the names here come from
metrics_contract.py so engine exporter and router scraper can't drift.
Runs as an asyncio task (the reference uses a daemon thread)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

import aiohttp
from prometheus_client.parser import text_string_to_metric_families

from .. import metrics_contract as mc
from ..utils.logging import init_logger

logger = init_logger(__name__)


@dataclass
class EngineStats:
    num_running_requests: float = 0
    num_queuing_requests: float = 0
    hbm_kv_usage_perc: float = 0.0
    prefix_cache_hit_rate: float = 0.0
    prefix_cache_hits_total: float = 0
    prefix_cache_queries_total: float = 0
    # peer-engine KV tier (docs/35-peer-kv-reuse.md): the two numbers the
    # priced route-vs-migrate scoring needs per engine — this engine's
    # measured peer-fetch bandwidth (tpu:kv_tier_bandwidth_bytes_per_s
    # {tier="peer",direction="in"}; the exporter renders 0.0 until the
    # TierBandwidth sample floor is crossed, so nonzero here really means
    # MEASURED and scoring below it keeps owner affinity / the
    # exploration rule) and its analytic KV bytes per token
    kv_peer_bw_in_bytes_per_s: float = 0.0
    # device-path peer pulls (docs/39-device-peer-kv.md): measured ICI/DCN
    # collective bandwidth {tier="device",direction="in"} — once nonzero
    # the migrate pricing uses max(peer, device), so the scoring shifts
    # toward migration automatically as the faster link gets measured
    kv_device_bw_in_bytes_per_s: float = 0.0
    kv_bytes_per_token: float = 0.0
    # pool rebalancing (docs/40-pool-rebalancing.md): the engine's LIVE
    # advertised role (tpu:pool_role sample at 1; "" = none advertised —
    # the routing policy then falls back to the static helm label), its
    # decode-seat occupancy EWMA, and the queue-wait p95 the scraper
    # computes over the scrape-to-scrape histogram delta (a cumulative-
    # histogram quantile would never decay, so cleared starvation would
    # look permanent)
    role: str = ""
    seat_occupancy: float = 0.0
    queue_wait_p95: float = 0.0
    # raw cumulative tpu:request_queue_wait_seconds bucket counts
    # (le -> count) — the scraper diffs consecutive scrapes
    queue_wait_buckets: dict[float, float] = field(default_factory=dict)

    _FIELDS = {
        mc.NUM_REQUESTS_RUNNING: "num_running_requests",
        mc.NUM_REQUESTS_WAITING: "num_queuing_requests",
        mc.HBM_KV_USAGE_PERC: "hbm_kv_usage_perc",
        mc.PREFIX_CACHE_HIT_RATE: "prefix_cache_hit_rate",
        mc.PREFIX_CACHE_HITS: "prefix_cache_hits_total",
        mc.PREFIX_CACHE_QUERIES: "prefix_cache_queries_total",
        mc.KV_BYTES_PER_TOKEN: "kv_bytes_per_token",
        mc.ENGINE_DECODE_SEAT_OCCUPANCY: "seat_occupancy",
    }

    @property
    def load(self) -> float:
        """Seat pressure the migrate scoring compares engines on:
        running + queued requests."""
        return self.num_running_requests + self.num_queuing_requests

    @classmethod
    def from_scrape(cls, text: str) -> "EngineStats":
        stats = cls()
        for family in text_string_to_metric_families(text):
            for sample in family.samples:
                # counters' samples keep the _total suffix the family drops
                field = cls._FIELDS.get(sample.name)
                if field is not None:
                    setattr(stats, field, sample.value)
                elif (
                    sample.name == mc.KV_TIER_BANDWIDTH
                    and sample.labels.get("direction") == "in"
                ):
                    tier = sample.labels.get("tier")
                    if tier == "peer":
                        stats.kv_peer_bw_in_bytes_per_s = sample.value
                    elif tier == "device":
                        stats.kv_device_bw_in_bytes_per_s = sample.value
                elif sample.name == mc.POOL_ROLE and sample.value >= 1:
                    stats.role = sample.labels.get("role", "")
                elif sample.name == mc.REQUEST_QUEUE_WAIT + "_bucket":
                    try:
                        le = float(sample.labels.get("le", ""))
                    except ValueError:
                        continue
                    stats.queue_wait_buckets[le] = (
                        stats.queue_wait_buckets.get(le, 0.0) + sample.value
                    )
        return stats


def _delta_p95(
    now: dict[float, float], prev: dict[float, float]
) -> float:
    """Queue-wait p95 over the scrape-to-scrape bucket delta — the
    router-side mirror of histogram_quantile(0.95, rate(...)). Returns
    the upper bound of the bucket the 95th percentile lands in (the same
    bound-not-interpolated estimate fleet.ConvergenceMeter uses); 0.0
    when no new observations arrived since the previous scrape."""
    if not now:
        return 0.0
    bounds = sorted(now)
    deltas = [max(0.0, now[b] - prev.get(b, 0.0)) for b in bounds]
    total = deltas[-1]  # cumulative buckets: +Inf carries the count
    if total <= 0:
        return 0.0
    target = 0.95 * total
    finite = [b for b in bounds if b != float("inf")]
    for bound, cum in zip(bounds, deltas):
        if cum >= target:
            if bound == float("inf"):
                # past every finite bucket: clamp to the largest finite
                # bound (histogram_quantile does the same)
                return finite[-1] if finite else 0.0
            return bound
    return 0.0


class EngineStatsScraper:
    def __init__(self, discovery, interval: float = 10.0):
        self.discovery = discovery
        self.interval = interval
        self._stats: dict[str, EngineStats] = {}
        # previous scrape's cumulative queue-wait buckets per engine —
        # the baseline the per-scrape p95 delta is computed against
        self._prev_buckets: dict[str, dict[float, float]] = {}
        self._task: asyncio.Task | None = None

    def get_engine_stats(self) -> dict[str, EngineStats]:
        return dict(self._stats)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def is_healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _loop(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except Exception as e:
                logger.warning("engine stats scrape failed: %s", e)
            await asyncio.sleep(self.interval)

    async def scrape_once(self) -> None:
        eps = self.discovery.endpoints()
        timeout = aiohttp.ClientTimeout(total=5)
        async with aiohttp.ClientSession(timeout=timeout) as sess:
            results = await asyncio.gather(
                *(self._scrape(sess, ep.url) for ep in eps)
            )
        fresh = {url: s for url, s in results if s is not None}
        for url, s in fresh.items():
            s.queue_wait_p95 = _delta_p95(
                s.queue_wait_buckets, self._prev_buckets.get(url, {})
            )
        # keep only live endpoints so dead engines don't pin stale stats
        # (and a restarted engine's counter reset reads as delta 0, not
        # a negative spike — _delta_p95 clamps at 0)
        self._prev_buckets = {
            url: s.queue_wait_buckets for url, s in fresh.items()
        }
        self._stats = fresh

    async def _scrape(self, sess, url: str):
        try:
            async with sess.get(url + "/metrics") as resp:
                if resp.status != 200:
                    return url, None
                return url, EngineStats.from_scrape(await resp.text())
        except Exception:
            return url, None
