"""Router CLI.

Three coordinated config layers like the reference (SURVEY §5): argparse CLI
(reference parsers/parser.py:118-386), an optional YAML/JSON config file whose
values become parser defaults (CLI wins), and the dynamic-config file watched
at runtime. Validation mirrors parser.py:85-115: static discovery requires
backends; session routing requires a session key; PD requires both label
lists."""

from __future__ import annotations

import argparse
import json
from pathlib import Path

import yaml

from .routing import ROUTING_POLICIES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU stack OpenAI-compatible router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8001)
    p.add_argument(
        "--config",
        default=None,
        help="YAML/JSON file of defaults for any flag (CLI values win)",
    )

    d = p.add_argument_group("service discovery")
    d.add_argument(
        "--service-discovery",
        choices=["static", "k8s_pod_ip", "k8s_service_name"],
        default="static",
    )
    d.add_argument(
        "--static-backends",
        default=None,
        help="comma-separated engine base URLs (static mode)",
    )
    d.add_argument(
        "--static-models",
        default=None,
        help="semicolon-separated per-backend comma-lists of model names",
    )
    d.add_argument(
        "--static-model-labels",
        default=None,
        help="comma-separated per-backend model labels (for PD pools)",
    )
    d.add_argument(
        "--health-probe-interval",
        type=float,
        default=None,
        help="seconds between static-backend health probes (off when unset)",
    )
    d.add_argument("--k8s-namespace", default="default")
    d.add_argument("--k8s-label-selector", default="")
    d.add_argument("--k8s-port", type=int, default=8000)

    r = p.add_argument_group("routing")
    r.add_argument("--routing-logic", choices=ROUTING_POLICIES, default="roundrobin")
    r.add_argument("--session-key", default=None, help="session header name")
    r.add_argument("--kv-controller-url", default=None)
    r.add_argument("--kv-aware-threshold", type=int, default=256)
    r.add_argument(
        "--kv-index-mode",
        choices=["controller", "embedded"],
        default="controller",
        help="kvaware lookup source: 'controller' asks the REST KV "
             "controller per request; 'embedded' hosts the event-driven "
             "cluster KV index in the router itself (engines publish to "
             "this router's /kv/events; point their KV_CONTROLLER_URL "
             "here) — zero lookup hops on the request path",
    )
    r.add_argument(
        "--kv-migrate-scoring",
        choices=["off", "priced"],
        default="off",
        help="route-vs-migrate policy once the KV-aware lookup finds a "
             "prefix owner (docs/35-peer-kv-reuse.md): 'off' always "
             "follows the owner (historical behavior); 'priced' compares "
             "the owner's queue wait against the least-loaded engine's "
             "wait plus the KV migration cost (matched tokens x scraped "
             "tpu:kv_bytes_per_token / measured peer fetch bandwidth) and "
             "on migrate stamps x-kv-owner-hint upstream so the target "
             "engine's hydration planner pulls the prefix from the owner "
             "(engines need --kv-peer-fetch for the pull; without it the "
             "target recomputes, which is still correct, just unpriced)",
    )
    r.add_argument(
        "--kv-index-tokenizer",
        default=None,
        help="embedded mode's shared tokenizer for hashing prompts the way "
             "engines do: an HF checkpoint/tokenizer dir, or 'byte' for "
             "the byte fallback (what tokenizer-less engines use)",
    )
    r.add_argument("--prefill-model-labels", default=None, help="comma-separated")
    r.add_argument("--decode-model-labels", default=None, help="comma-separated")
    r.add_argument(
        "--model-aliases",
        default=None,
        help='JSON object {"alias": "served-model"}',
    )

    u = p.add_argument_group("upstream robustness")
    u.add_argument(
        "--upstream-sock-read-s", type=float, default=300.0,
        help="per-read upstream timeout (seconds) on proxied requests — a "
             "wedged engine that stops sending bytes severs the client "
             "instead of hanging it forever. Streaming-safe: active decode "
             "emits chunks sub-second, so only a truly stalled upstream "
             "trips it (0 = no guard)",
    )
    u.add_argument(
        "--upstream-total-s", type=float, default=0.0,
        help="whole-request upstream timeout (seconds), 0 = unlimited. "
             "Leave 0 for streaming/transcription workloads (a legitimate "
             "long answer is not a fault) and rely on --upstream-sock-read-s",
    )
    u.add_argument(
        "--upstream-connector-limit", type=int, default=0,
        help="max concurrent upstream connections held by the proxy "
             "(0 = unlimited, the default). aiohttp's own default of 100 "
             "would silently queue a 10k-concurrent-stream replica behind "
             "100 upstream sockets (docs/34-fleet-routing.md)",
    )
    u.add_argument(
        "--default-deadline-ms", type=float, default=0.0,
        help="inject x-request-deadline-ms on proxied requests that don't "
             "carry one: engines shed work they can't start in time (429/"
             "503) and abort decodes whose caller has given up (0 = off)",
    )
    u.add_argument(
        "--breaker-failure-threshold", type=int, default=5,
        help="consecutive upstream failures that open an endpoint's "
             "circuit breaker (excluded from policy picks until a "
             "half-open probe succeeds; backoff doubles per re-open). "
             "0 disables breakers",
    )
    u.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                   help="initial open-state cooldown before the half-open "
                        "probe")
    u.add_argument("--breaker-max-cooldown-s", type=float, default=120.0,
                   help="backoff ceiling for endpoints that keep failing "
                        "their half-open probes")

    s = p.add_argument_group("stats")
    s.add_argument("--engine-stats-interval", type=float, default=10.0)
    s.add_argument("--request-stats-window", type=float, default=60.0)
    s.add_argument("--log-stats-interval", type=float, default=0.0,
                   help="seconds between stats log lines (0 = off)")

    f = p.add_argument_group("fleet coherence (docs/32-fleet-telemetry.md)")
    f.add_argument(
        "--router-replica-id", default=None,
        help="this router replica's identity, stamped on upstream requests "
             "(x-router-replica-id) and on fleet reports. Default: the "
             "hostname — which on k8s is the pod name, already unique per "
             "replica",
    )
    f.add_argument(
        "--fleet-report-url", default=None,
        help="base URL of the fleet aggregation endpoint (the KV "
             "controller hosts POST /fleet/report + GET /fleet). Defaults "
             "to --kv-controller-url when that is set; unset = no "
             "reporting",
    )
    f.add_argument(
        "--fleet-report-interval", type=float, default=10.0,
        help="seconds between fleet coherence reports (ring membership "
             "hash, embedded KV-index positions, breaker states, "
             "per-tenant drained counters) POSTed to --fleet-report-url; "
             "0 disables reporting even with a URL configured. Jittered "
             "±15%% so replicas don't tick in lockstep",
    )
    f.add_argument(
        "--fleet-budget-scaling", choices=["on", "off"], default="on",
        help="scale local tenant token buckets to a 1/M share of each "
             "tenant's global budget, M = the live replica count from the "
             "controller's /fleet/report reply (docs/34-fleet-routing.md) "
             "— closes the N-replica over-admission gap (~N-1x) without a "
             "synchronous hop on admission. Degrades to the full local "
             "budget when the controller goes silent past 3 report "
             "intervals. Needs fleet reporting and a tenant table; 'off' "
             "restores the report-only PR 9 behavior",
    )

    x = p.add_argument_group("extensions")
    x.add_argument("--dynamic-config-file", default=None)
    x.add_argument("--dynamic-config-interval", type=float, default=10.0)
    x.add_argument("--callbacks", default=None, help="module[:Class] or path.py")
    x.add_argument("--request-rewriter", default=None, help="module:Class")
    x.add_argument("--feature-gates", default="")
    x.add_argument("--pii-analyzer", default="regex",
                   choices=["regex", "presidio"],
                   help="PII analyzer backend (presidio needs the "
                        "presidio-analyzer package in the router image)")
    x.add_argument("--api-key", default=None, help="require this bearer token")

    q = p.add_argument_group("multi-tenant QoS (docs/27-multitenancy.md)")
    q.add_argument(
        "--tenant-table-file", default=None,
        help="YAML/JSON tenant policy table (per-tenant API keys, priority "
             "class realtime|standard|batch, fair-share weight, "
             "requests_per_s / tokens_per_min / max_concurrent limits). "
             "Enables the QoS gate: callers resolve to a tenant, get "
             "per-tenant rate limits BEFORE routing, and requests are "
             "stamped x-tenant-id/x-priority/x-tenant-weight for the "
             "engines' weighted fair-share scheduler. Hot-reloaded by the "
             "dynamic-config watcher when --dynamic-config-file is set",
    )
    q.add_argument(
        "--qos-tokenizer", default="byte",
        help="tokenizer for the tokens-per-minute buckets: an HF "
             "checkpoint/tokenizer dir (count exactly like the engines), "
             "'byte' for the dependency-free byte fallback, or '' to "
             "meter requests only",
    )
    x.add_argument("--sentry-dsn", default=None,
                   help="enable Sentry error reporting (requires sentry-sdk)")
    x.add_argument("--sentry-traces-sample-rate", type=float, default=0.0)
    x.add_argument(
        "--request-tracing", choices=["on", "off"], default="on",
        help="per-request span timelines (docs/28-request-tracing.md): "
             "routing decision, failover attempts, QoS verdicts, upstream "
             "TTFB — joined to the engines' spans via the propagated W3C "
             "traceparent header and served by /debug/requests. 'off' "
             "keeps only the tpu:request_* latency histograms",
    )
    x.add_argument(
        "--trace-buffer", type=int, default=512,
        help="finished request timelines kept in the in-process ring "
             "buffer behind /debug/requests",
    )
    x.add_argument(
        "--event-loop-lag-interval-s", type=float, default=0.5,
        help="asyncio event-loop starvation probe interval (docs/37-"
             "flight-recorder.md): a short repeating sleep whose overshoot "
             "is exported as tpu:router_event_loop_lag_seconds (decaying "
             "peak) — a starved loop serves nothing while every "
             "request-vantage metric just goes quiet. 0 disables",
    )
    x.add_argument("--enable-batch-api", action="store_true")
    x.add_argument("--files-dir", default="/tmp/tpu_router_files")
    x.add_argument("--batch-db", default="/tmp/tpu_router_batch.sqlite")
    x.add_argument(
        "--semantic-cache-dir", default=None,
        help="semantic-cache embedder: a sentence-transformers model dir; "
             "'engine' to embed through a backend's /v1/embeddings (REAL "
             "model vectors, zero extra deps); 'hashing' for the "
             "lexical bag-of-words fallback (gate SemanticCache)",
    )
    x.add_argument("--semantic-cache-threshold", type=float, default=0.9)
    return p


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    parser = build_parser()
    # first pass just to find --config; file values become defaults, CLI wins
    pre, _ = parser.parse_known_args(argv)
    if pre.config:
        text = Path(pre.config).read_text()
        loaded = (
            json.loads(text) if pre.config.endswith(".json") else yaml.safe_load(text)
        ) or {}
        defaults = {k.replace("-", "_"): v for k, v in loaded.items()}
        known = {a.dest for a in parser._actions}
        unknown = set(defaults) - known
        if unknown:
            parser.error(f"unknown keys in --config file: {sorted(unknown)}")
        parser.set_defaults(**defaults)
    args = parser.parse_args(argv)
    validate_args(parser, args)
    if not args.router_replica_id:
        # hostname == pod name on k8s: unique per replica with zero config
        import socket

        args.router_replica_id = socket.gethostname()
    # NOTE: the fleet-report-url → kv-controller-url fallback lives in ONE
    # place, app.build_app's startup (it must cover programmatically
    # constructed args too, which never pass through here)
    return args


def validate_args(parser: argparse.ArgumentParser, args) -> None:
    if args.service_discovery == "static" and not args.static_backends:
        parser.error("--service-discovery static requires --static-backends")
    if args.routing_logic == "session" and not args.session_key:
        parser.error("--routing-logic session requires --session-key")
    if args.routing_logic == "kvaware":
        if args.kv_index_mode == "controller" and not args.kv_controller_url:
            parser.error(
                "--routing-logic kvaware requires --kv-controller-url "
                "(or --kv-index-mode embedded)"
            )
        if args.kv_index_mode == "embedded" and not args.kv_index_tokenizer:
            parser.error(
                "--kv-index-mode embedded requires --kv-index-tokenizer "
                "(a tokenizer dir, or 'byte')"
            )
    if args.routing_logic == "disaggregated_prefill" and not (
        args.prefill_model_labels and args.decode_model_labels
    ):
        parser.error(
            "--routing-logic disaggregated_prefill requires "
            "--prefill-model-labels and --decode-model-labels"
        )
    if args.static_models and args.static_backends:
        n_b = len(args.static_backends.split(","))
        n_m = len(args.static_models.split(";"))
        if n_b != n_m:
            parser.error(
                f"--static-models has {n_m} groups for {n_b} backends"
            )
