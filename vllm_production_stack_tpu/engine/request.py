"""Request/sequence state for the serving engine.

The reference stack's requests live as OpenAI JSON bodies proxied to vLLM
(src/vllm_router/services/request_service/request.py); inside our TPU engine
each becomes a `Request` tracked by the scheduler through the continuous-
batching lifecycle.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class SamplingParams:
    max_tokens: int = 128
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    stop: tuple[str, ...] = ()
    stop_token_ids: tuple[int, ...] = ()
    ignore_eos: bool = False
    # vLLM extension: suppress eos/stop-token finishes until this many
    # output tokens exist (stop STRINGS and length caps still apply)
    min_tokens: int = 0
    seed: int | None = None
    # OpenAI logprobs: None = off; N = return the chosen token's logprob
    # plus the top-N alternatives per generated token (N <= runner
    # LOGPROBS_TOPN; 0 = chosen-only)
    logprobs: int | None = None
    # structured output (docs/41-structured-output.md): the COMPILED
    # grammar.TokenGrammar this request's generation must satisfy, or None
    # for unconstrained. Compiled once at the API layer (GrammarCache) and
    # shared across requests; compared by identity, which is exactly the
    # sharing semantics the runner's device-table cache keys on.
    grammar: object | None = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


class RequestStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED_STOPPED = "finished_stopped"  # eos / stop string
    FINISHED_LENGTH = "finished_length"  # max_tokens / max_model_len
    FINISHED_ABORTED = "finished_aborted"
    # deadline expired while queued or decoding — aborted with a clean
    # "deadline" finish reason instead of burning further TPU steps
    FINISHED_DEADLINE = "finished_deadline"
    # evicted from the waiting queue by a higher-priority admission when
    # the queue bound was full (multi-tenant QoS: lowest-priority-first
    # shedding, docs/27-multitenancy.md) — the HTTP layer maps this back
    # to a 429 + Retry-After
    FINISHED_SHED = "finished_shed"

    @property
    def finished(self) -> bool:
        return self in (
            RequestStatus.FINISHED_STOPPED,
            RequestStatus.FINISHED_LENGTH,
            RequestStatus.FINISHED_ABORTED,
            RequestStatus.FINISHED_DEADLINE,
            RequestStatus.FINISHED_SHED,
        )


@dataclass(eq=False)  # identity semantics: requests live in sets/queues
class Request:
    request_id: str
    prompt_token_ids: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: int | None = None
    arrival_time: float = field(default_factory=time.monotonic)

    # adapter slot in the runner's stacked LoRA buffers (0 = base model)
    lora_index: int = 0
    # stable per-adapter-load salt for the KV hash chain (0 = base model).
    # Slot numbers get REUSED across adapter loads, so the prefix cache keys
    # on this instead: adapter KV differs from base KV whenever k/v
    # projections carry deltas, and cross-matching would be silent corruption
    lora_cache_salt: int = 0

    status: RequestStatus = RequestStatus.WAITING
    output_token_ids: list[int] = field(default_factory=list)
    # blocks owned by this request, logical order (block_table[i] = page of
    # tokens [i*block_size, (i+1)*block_size))
    block_table: list[int] = field(default_factory=list)
    # tokens whose KV is resident (prefix-cache hits + computed prefill/decode)
    num_computed_tokens: int = 0
    num_cached_prompt_tokens: int = 0  # prefix-cache hits at admission
    # lifecycle stamps (time.monotonic()) behind the tracing spine's phase
    # attribution (docs/28-request-tracing.md): queue wait = first_seat -
    # arrival, prefill = first_token - first_seat, decode = finish -
    # first_token. first_seat_time is the FIRST admission only — a
    # preempted request re-admitting keeps its original stamp, so phases
    # describe the caller-visible lifecycle, not scheduler churn.
    first_seat_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None
    num_preemptions: int = 0
    # tokens dispatched in not-yet-resolved device steps (async pipeline):
    # the scheduler plans the NEXT step at num_computed_tokens +
    # num_inflight_tokens and treats the in-flight window as generated for
    # max_tokens/window clamping; postprocess of the resolved step moves
    # these into num_computed_tokens / output_token_ids for real
    num_inflight_tokens: int = 0
    # goodput ledger (engine/saturation.GoodputLedger): accepted output
    # tokens not yet classified delivered/wasted. Charged in postprocess,
    # settled exactly once at finish (delivered for stop/length,
    # wasted{reason} otherwise). Pending SURVIVES preemption — the token
    # values live on in output_token_ids, so their fate is still open; the
    # recompute cost is charged separately (preempted_recompute) when
    # resumed prefill re-processes generated positions.
    ledger_pending: int = 0
    # hydration attribution (docs/30-kv-flow-telemetry.md): where this
    # request's prompt-token KV came from, classified EXACTLY once at first
    # admission — {hbm_hit, host_reload, disk_load, remote_fetch,
    # recomputed} tokens summing to num_prompt_tokens. None until seated
    # (and forever for requests refused before a seat).
    hydration: dict | None = None
    # compute-or-load hydration planner (docs/31-hydration-planner.md):
    # the live chunk plan over this request's lower-tier-resident prefix
    # (engine/hydration.HydrationPlan), None when no plan is active —
    # cleared when fully consumed, cancelled on preempt/finish.
    hydration_plan: object | None = None
    # per-chunk outcome records appended as chunks resolve — surfaced on
    # the terminal output for the kv_hydration trace event's plan view
    hydration_outcomes: list | None = None
    # absolute time.monotonic() after which this request is worthless to its
    # caller (x-request-deadline-ms, carried router → engine → scheduler);
    # None = no deadline. The scheduler sweeps expired requests out of
    # waiting/running at the top of every schedule() call.
    deadline: float | None = None
    # multi-tenant QoS (docs/27-multitenancy.md), from the router-stamped
    # x-tenant-id / x-priority / x-tenant-weight headers. priority is the
    # RANK (0 realtime, 1 standard, 2 batch): lower wins admission,
    # higher is preempted/shed first. Unstamped traffic carries the
    # defaults and collapses to the pre-QoS FIFO behavior.
    tenant_id: str = "default"
    priority: int = 1
    weight: float = 1.0
    # peer-engine KV tier (docs/35-peer-kv-reuse.md): the router's
    # x-kv-owner-hint — the engine URL whose tiers hold this prompt's
    # prefix, stamped when priced route-vs-migrate sent the request AWAY
    # from the owner. The hydration planner's probe uses it to skip the
    # cluster-index rediscovery hop. None = rediscover (or no peer tier).
    kv_owner_hint: str | None = None
    # speculative decoding (docs/36-speculative-decoding.md): the LAST
    # resolved verify window's (proposed, accepted, proposer) — stamped by
    # postprocess, moved onto that step's RequestOutput by _make_output
    # (and cleared), so the tracing spine's decode_window event carries
    # per-window acceptance
    spec_window: tuple | None = None
    # pipelined spec-decode retry budget (scheduler.SPEC_RETRY_WINDOWS):
    # chained decode windows left to ride after a failed propose attempt
    # before the row sits one step out to re-propose on resolved values
    spec_retry_in: int = 0
    # structured output (docs/41-structured-output.md): per-request
    # automaton cursor (grammar.GrammarState), None when unconstrained.
    # Advanced ONLY on accepted tokens in scheduler.postprocess — so it
    # needs no rollback of its own (discarded speculative steps never
    # touched it) and survives preemption with output_token_ids.
    grammar: object | None = None
    # compile telemetry (docs/42-compile-telemetry.md): mid-traffic XLA
    # compiles this request's dispatches blocked on — {phase, key,
    # wall_ms} dicts stamped by the runner, moved onto the terminal
    # output by _make_output for the trace timeline's compile_stall
    # events. None (the steady state) = never stalled.
    compile_stalls: list | None = None

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def prefill_target(self) -> int:
        """Tokens whose KV must be resident before decode can run. For a fresh
        request that's the whole prompt; for a preempted-then-resumed request
        (which already has outputs to recompute) it's everything except the
        last token — that one is the next decode step's input."""
        if self.output_token_ids:
            return self.num_tokens - 1
        return self.num_prompt_tokens

    @property
    def prefill_done(self) -> bool:
        return self.num_computed_tokens >= self.prefill_target

    def token_at(self, idx: int) -> int:
        np_ = len(self.prompt_token_ids)
        return (
            self.prompt_token_ids[idx]
            if idx < np_
            else self.output_token_ids[idx - np_]
        )


@dataclass
class RequestOutput:
    """Per-step incremental output handed to the API layer."""

    request_id: str
    new_token_ids: list[int]
    finished: bool
    finish_reason: str | None = None  # "stop" | "length" | "abort"
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    num_cached_prompt_tokens: int = 0
    text_delta: str = ""
    # aligned with new_token_ids when the request asked for logprobs:
    # one (chosen_logprob, top_ids, top_logprobs) triple per token
    new_logprobs: list[tuple[float, list[int], list[float]]] | None = None
    # set on the TERMINAL output only: the request's lifecycle stamps
    # (time.monotonic(): arrival/first_seat/first_token/finish, None where
    # a phase never happened) — the HTTP layer turns these into trace
    # phase spans and the tpu:request_* histograms without reaching back
    # into engine state that _drop_finished already reaped
    phase_times: dict | None = None
    # terminal output only: the request's hydration-source partition
    # (Request.hydration) — the HTTP layer emits it as the timeline's
    # kv_hydration event (docs/30-kv-flow-telemetry.md)
    hydration: dict | None = None
    # terminal output only: the hydration planner's per-chunk outcomes
    # (Request.hydration_outcomes) — the kv_hydration trace event's
    # "plan" attribute (docs/31-hydration-planner.md)
    hydration_chunks: list | None = None
    # set when this step resolved a speculative-verify window for the
    # request: (proposed, accepted, proposer) — the tracing spine adds it
    # to the decode_window event (docs/36-speculative-decoding.md)
    spec_window: tuple | None = None
    # terminal output only, constrained requests only: "valid" when the
    # automaton finished in an accepting state (the body parses against
    # the schema by construction), "invalid" when terminated mid-structure
    # (length cut / abort), "fallback" when constraints were requested but
    # not applied (docs/41-structured-output.md)
    structured_outcome: str | None = None
    # terminal output only: mid-traffic compile stalls this request's
    # dispatches blocked on (Request.compile_stalls) — each becomes a
    # compile_stall event on the trace timeline
    # (docs/42-compile-telemetry.md)
    compile_stalls: list | None = None
