"""OpenAI-compatible API schema (pydantic) for the engine server.

Mirrors the surface the reference stack proxies to its vLLM engines
(src/vllm_router/routers/main_router.py:50-246): chat completions,
completions, models. Extra fields are tolerated and ignored (the reference's
protocols.py logs-and-allows extras too)."""

from __future__ import annotations

import time
import uuid

from pydantic import BaseModel, ConfigDict, Field

from .request import SamplingParams


class OpenAIModel(BaseModel):
    model_config = ConfigDict(extra="allow")


class ChatMessage(OpenAIModel):
    role: str
    content: str | list | None = None


class StreamOptions(OpenAIModel):
    include_usage: bool = False


class EmbeddingRequest(OpenAIModel):
    model: str
    input: str | list
    encoding_format: str = "float"
    dimensions: int | None = None
    user: str | None = None


class ChatCompletionRequest(OpenAIModel):
    model: str
    messages: list[ChatMessage]
    # OpenAI tool calling (engine/tool_calls.py — Hermes-style convention;
    # tool_choice: "auto" | "none" | "required" | {"type":"function",...})
    tools: list[dict] | None = None
    tool_choice: str | dict | None = None
    # structured output (docs/41-structured-output.md):
    # response_format: {"type": "json_object"} or
    # {"type": "json_schema", "json_schema": {"name":..., "schema":...}}
    # guided_json is the vLLM-compatible shorthand (the schema itself).
    response_format: dict | None = None
    guided_json: dict | bool | None = None  # extension (vLLM-compatible)
    max_tokens: int | None = None
    max_completion_tokens: int | None = None
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # extension (vLLM-compatible)
    n: int = 1
    stream: bool = False
    stream_options: StreamOptions | None = None
    stop: str | list[str] | None = None
    stop_token_ids: list[int] | None = None  # extension (vLLM-compatible)
    seed: int | None = None
    user: str | None = None
    ignore_eos: bool = False  # extension (benchmark harnesses rely on it)
    min_tokens: int = 0  # extension (vLLM-compatible)
    logprobs: bool = False
    top_logprobs: int | None = None

    def sampling(self, default_max_tokens: int) -> SamplingParams:
        stop = self.stop if self.stop is not None else []
        if isinstance(stop, str):
            stop = [stop]
        return SamplingParams(
            max_tokens=self.max_completion_tokens
            or self.max_tokens
            or default_max_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            stop=tuple(stop),
            stop_token_ids=tuple(self.stop_token_ids or ()),
            seed=self.seed,
            ignore_eos=self.ignore_eos,
            min_tokens=self.min_tokens,
            logprobs=(
                (self.top_logprobs or 0) if self.logprobs else None
            ),
        )


class CompletionRequest(OpenAIModel):
    model: str
    prompt: str | list[str] | list[int] | list[list[int]]
    max_tokens: int | None = None
    # structured output (docs/41-structured-output.md)
    response_format: dict | None = None
    guided_json: dict | bool | None = None  # extension (vLLM-compatible)
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    n: int = 1
    stream: bool = False
    stream_options: StreamOptions | None = None
    stop: str | list[str] | None = None
    stop_token_ids: list[int] | None = None  # extension (vLLM-compatible)
    seed: int | None = None
    echo: bool = False
    user: str | None = None
    ignore_eos: bool = False
    min_tokens: int = 0  # extension (vLLM-compatible)
    logprobs: int | None = None

    def sampling(self, default_max_tokens: int) -> SamplingParams:
        stop = self.stop if self.stop is not None else []
        if isinstance(stop, str):
            stop = [stop]
        return SamplingParams(
            max_tokens=self.max_tokens or default_max_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            stop=tuple(stop),
            stop_token_ids=tuple(self.stop_token_ids or ()),
            seed=self.seed,
            ignore_eos=self.ignore_eos,
            min_tokens=self.min_tokens,
            logprobs=self.logprobs,
        )


class ScoreRequest(OpenAIModel):
    """vLLM /v1/score shape (the reference router proxies it to its engines,
    main_router.py:50-246): text_1 x text_2 similarity. One-vs-many when
    text_1 is a single string, elementwise when both are equal-length
    lists."""

    model: str
    text_1: str | list[str]
    text_2: str | list[str]


class RerankRequest(OpenAIModel):
    """Jina/Cohere-style rerank shape served by vLLM engines
    (/v1/rerank): rank `documents` by relevance to `query`."""

    model: str
    query: str
    documents: list[str]
    top_n: int | None = None
    return_documents: bool = True


class UsageInfo(OpenAIModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


def usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return UsageInfo(
        prompt_tokens=prompt_tokens,
        completion_tokens=completion_tokens,
        total_tokens=prompt_tokens + completion_tokens,
    ).model_dump()


class ModelCard(OpenAIModel):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "tpu-serving-stack"
    root: str | None = None
    parent: str | None = None


class ModelList(OpenAIModel):
    object: str = "list"
    data: list[ModelCard] = Field(default_factory=list)


class ErrorResponse(OpenAIModel):
    object: str = "error"
    message: str
    type: str = "invalid_request_error"
    code: int = 400


def random_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"
