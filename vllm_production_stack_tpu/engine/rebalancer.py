"""Prefill/decode pool rebalancer (docs/40-pool-rebalancing.md).

The role-flip actuator that closes the loop `TpuSeatStarvation` opens:
disaggregated pools are born statically partitioned (helm modelLabel),
so a workload shift strands one pool starved while the other idles.
BanaServe (PAPERS.md) argues the P:D ratio must follow the workload;
this module is the production-shaped version of that argument — hosted
in the KV controller, fed by the per-pool signals router replicas
already report (router/fleet.py `pools`), actuating through the
engine's existing drain barrier plus the new POST /role endpoint.

Robustness is the design center, not the happy path:

- Every flip is an explicit EPISODE with a persisted phase
  (`observe → cooldown → drain → flip → rejoin → verify`), written
  atomically to `state_file` on every transition. A controller crash
  mid-flip resumes the episode from its persisted phase on restart —
  or abandons it when it has aged past `episode_timeout_s` (safe:
  drain and flip are idempotent, and an engine restart restores its
  static `--pool-role`).
- A controller outage fails OPEN: engines only ever act on explicit
  POSTs, so a dead controller leaves every engine serving under its
  last role (the PR 12 fail-open idiom — coherence may degrade,
  availability never does).
- A flip that makes the starved pool WORSE within the verify window is
  rolled back exactly once and the engine goes on cooldown, so a
  mis-diagnosed imbalance cannot oscillate an engine between roles.
- Hysteresis (`observe_s` of SUSTAINED imbalance before acting) plus
  min-pool-size floors guarantee the actuator can never drain the last
  engine of either role.

The tick loop beats a liveness heartbeat ("rebalancer" in the
THREAD_NAME_VALUES closed set) so the PR 15 watchdog machinery names a
wedged rebalancer instead of letting starvation quietly persist.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from dataclasses import dataclass, field

from .. import metrics_contract as mc
from ..utils.logging import init_logger

logger = init_logger(__name__)

# transitional phases belong to an active episode; observe/cooldown are
# the idle phases (the TpuRebalanceStuck alert keys off the transitional
# set staying pinned)
TRANSITIONAL_PHASES = ("drain", "flip", "rejoin", "verify")


@dataclass
class RebalanceConfig:
    enabled: bool = False
    # tick cadence of the state machine; every phase advances at most
    # once per tick, so drain/flip retries are naturally paced by it
    interval_s: float = 2.0
    # hysteresis: one imbalance DIRECTION must hold for this long before
    # an episode starts (a single hot scrape must not flip an engine)
    observe_s: float = 10.0
    # global hold-off after any finished episode before the next may start
    cooldown_s: float = 60.0
    # how long a completed flip gets to prove itself before the verdict;
    # a starved-pool queue wait WORSE than the episode baseline within
    # this window triggers the single rollback
    verify_window_s: float = 30.0
    # min-pool-size floors: an episode never starts if flipping would
    # leave the source pool below its floor — the actuator structurally
    # cannot drain the last engine of either role
    min_prefill: int = 1
    min_decode: int = 1
    # imbalance thresholds, mirroring the TpuSeatStarvation rule
    # (queue-wait p95 > 1s while decode seats sit < 50% full)
    queue_wait_trigger_s: float = 1.0
    occupancy_rich_max: float = 0.5
    # bound on the POST /drain?wait=true barrier per attempt
    drain_timeout_s: float = 30.0
    # consecutive unreachable-engine ticks before the episode is
    # abandoned (the engine's restart restores its static role)
    unreachable_limit: int = 5
    # wall-clock bound on a whole episode — a resumed-from-crash episode
    # older than this is abandoned instead of replayed
    episode_timeout_s: float = 600.0
    # per-engine hold-off after a rollback (the "engine pair on
    # cooldown" rule: the flipped engine sits out this long)
    engine_cooldown_s: float = 300.0
    # persisted state (episode phase + outcome counters); "" = in-memory
    # only (tests; a restart then starts from observe, which is safe)
    state_file: str = ""


@dataclass
class Episode:
    """One flip attempt, JSON-persisted field-for-field."""

    seq: int
    engine: str
    from_role: str
    to_role: str
    phase: str  # drain | flip | rejoin | verify
    started_ts: float  # wall clock — survives restarts
    phase_ts: float
    # the starved pool + its queue wait when the episode started: the
    # verify verdict compares against this
    starved_role: str
    baseline_queue_wait: float
    rolled_back: bool = False
    unreachable: int = 0

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "engine": self.engine,
            "from_role": self.from_role,
            "to_role": self.to_role,
            "phase": self.phase,
            "started_ts": self.started_ts,
            "phase_ts": self.phase_ts,
            "starved_role": self.starved_role,
            "baseline_queue_wait": self.baseline_queue_wait,
            "rolled_back": self.rolled_back,
            "unreachable": self.unreachable,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Episode":
        return cls(
            seq=int(d.get("seq") or 0),
            engine=str(d.get("engine") or ""),
            from_role=str(d.get("from_role") or ""),
            to_role=str(d.get("to_role") or ""),
            phase=str(d.get("phase") or "drain"),
            started_ts=float(d.get("started_ts") or 0.0),
            phase_ts=float(d.get("phase_ts") or 0.0),
            starved_role=str(d.get("starved_role") or ""),
            baseline_queue_wait=float(d.get("baseline_queue_wait") or 0.0),
            rolled_back=bool(d.get("rolled_back")),
            unreachable=int(d.get("unreachable") or 0),
        )


@dataclass
class _PoolView:
    """One direction-evaluation input: both pools, split by live role."""

    prefill: dict[str, dict] = field(default_factory=dict)
    decode: dict[str, dict] = field(default_factory=dict)

    def pool(self, role: str) -> dict[str, dict]:
        return self.prefill if role == "prefill" else self.decode


def _max_queue_wait(pool: dict[str, dict]) -> float:
    return max(
        (p.get("queue_wait_p95", 0.0) for p in pool.values()), default=0.0
    )


def _mean_occupancy(pool: dict[str, dict]) -> float:
    if not pool:
        return 0.0
    return sum(p.get("seat_occupancy", 0.0) for p in pool.values()) / len(pool)


class PoolRebalancer:
    """Crash-safe role-flip state machine; one instance per controller.

    `pool_stats_fn` returns the merged fleet view (url -> {role,
    queue_wait_p95, seat_occupancy, load}); `session_fn` is an async
    callable yielding the controller's shared aiohttp session;
    `registered_roles_fn` returns the roles engines advertised at
    registration (fresher than the scrape-lagged fleet view right after
    a flip — the engine itself is the authority). `now_fn` is injectable
    so tests drive the clock."""

    def __init__(self, config: RebalanceConfig, pool_stats_fn,
                 session_fn, registered_roles_fn=None, heartbeat=None,
                 now_fn=time.time):
        self.config = config
        self.pool_stats_fn = pool_stats_fn
        self.session_fn = session_fn
        self.registered_roles_fn = registered_roles_fn or (lambda: {})
        self.heartbeat = heartbeat
        self.now_fn = now_fn
        self.episode: Episode | None = None
        self.flips: dict[str, int] = {o: 0 for o in
                                      mc.POOL_REBALANCE_OUTCOME_VALUES}
        self.episodes_started = 0
        self.cooldown_until: float = 0.0
        self.engine_cooldown_until: dict[str, float] = {}
        # hysteresis tracker: (starved_role, first-seen wall clock)
        self._imbalance_since: tuple[str, float] | None = None
        self.last_error: str | None = None
        self._task: asyncio.Task | None = None
        self._load_state()

    # -- persistence -------------------------------------------------------

    def _load_state(self) -> None:
        path = self.config.state_file
        if not path or not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                state = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("rebalancer state unreadable (%s); starting "
                           "fresh", e)
            return
        for outcome, n in (state.get("flips") or {}).items():
            if outcome in self.flips:
                self.flips[outcome] = int(n)
        self.episodes_started = int(state.get("episodes_started") or 0)
        self.cooldown_until = float(state.get("cooldown_until") or 0.0)
        raw = state.get("episode")
        if raw:
            ep = Episode.from_dict(raw)
            # resume counts from zero unreachable ticks — the crash may
            # have been ours, not the engine's
            ep.unreachable = 0
            self.episode = ep
            logger.info(
                "resuming rebalance episode %d (%s -> %s, phase=%s) "
                "from persisted state",
                ep.seq, ep.engine, ep.to_role, ep.phase,
            )

    def _save_state(self) -> None:
        path = self.config.state_file
        if not path:
            return
        state = {
            "version": 1,
            "flips": self.flips,
            "episodes_started": self.episodes_started,
            "cooldown_until": self.cooldown_until,
            "episode": self.episode.to_dict() if self.episode else None,
        }
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(state, f)
            os.replace(tmp, path)  # atomic: a crash never half-writes
        except OSError as e:
            logger.warning("rebalancer state persist failed: %s", e)

    # -- loop --------------------------------------------------------------

    def start(self) -> None:
        if self.config.enabled and self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # the actuator must outlive any fault
                self.last_error = f"{type(e).__name__}: {e}"
                logger.warning("rebalancer tick failed: %s", e)
            await asyncio.sleep(self.config.interval_s)

    async def tick(self) -> None:
        """One state-machine step — also the unit tests' entry point."""
        if self.heartbeat is not None:
            self.heartbeat.beat()
        if self.episode is not None:
            await self._advance()
        else:
            self._evaluate()

    # -- observation -------------------------------------------------------

    def _pool_view(self) -> _PoolView:
        """Both pools under LIVE roles: registration-advertised role wins
        (the engine is the authority and reports it the moment a flip
        lands), the router-scraped role covers engines that registered
        before roles existed."""
        view = _PoolView()
        reg = dict(self.registered_roles_fn() or {})
        for url, p in (self.pool_stats_fn() or {}).items():
            role = reg.get(url) or p.get("role") or ""
            if role in ("prefill", "decode"):
                view.pool(role)[url] = p
        return view

    def _diagnose(self, view: _PoolView) -> str | None:
        """Which pool is starved, or None. Mirrors TpuSeatStarvation:
        work queues while the other pool's capacity sits idle.

        - "prefill" starved: prefill queue wait past the trigger while
          decode seats sit below occupancy_rich_max (decode is rich).
        - "decode" starved: decode queue wait past the trigger, decode
          seats ABOVE the rich ceiling (genuinely busy), prefill quiet
          (prefill is rich)."""
        cfg = self.config
        if not view.prefill or not view.decode:
            return None  # not a (complete) disaggregated deployment
        prefill_qw = _max_queue_wait(view.prefill)
        decode_qw = _max_queue_wait(view.decode)
        decode_occ = _mean_occupancy(view.decode)
        if (prefill_qw > cfg.queue_wait_trigger_s
                and decode_occ < cfg.occupancy_rich_max):
            return "prefill"
        if (decode_qw > cfg.queue_wait_trigger_s
                and decode_occ >= cfg.occupancy_rich_max
                and prefill_qw <= cfg.queue_wait_trigger_s / 2):
            return "decode"
        return None

    def _evaluate(self) -> None:
        now = self.now_fn()
        if now < self.cooldown_until:
            return  # phase renders as "cooldown"
        view = self._pool_view()
        starved = self._diagnose(view)
        if starved is None:
            self._imbalance_since = None
            return
        # hysteresis: the SAME direction must hold for observe_s
        if (self._imbalance_since is None
                or self._imbalance_since[0] != starved):
            self._imbalance_since = (starved, now)
            return
        if now - self._imbalance_since[1] < self.config.observe_s:
            return
        rich = "decode" if starved == "prefill" else "prefill"
        rich_pool = view.pool(rich)
        floor = (self.config.min_decode if rich == "decode"
                 else self.config.min_prefill)
        if len(rich_pool) - 1 < floor:
            # flipping would drop the rich pool below its floor — the
            # last-engine guarantee. Keep observing; scale-up is the
            # operator's move here, not a flip.
            return
        candidates = {
            url: p for url, p in rich_pool.items()
            if now >= self.engine_cooldown_until.get(url, 0.0)
        }
        if not candidates:
            return
        # least-loaded engine in the rich pool pays the smallest drain
        target = min(
            candidates, key=lambda u: (candidates[u].get("load", 0.0), u)
        )
        self.episodes_started += 1
        self.episode = Episode(
            seq=self.episodes_started,
            engine=target,
            from_role=rich,
            to_role=starved,
            phase="drain",
            started_ts=now,
            phase_ts=now,
            starved_role=starved,
            baseline_queue_wait=_max_queue_wait(view.pool(starved)),
        )
        self._imbalance_since = None
        self._save_state()
        logger.info(
            "rebalance episode %d: %s pool starved -> draining %s "
            "(%s -> %s, baseline queue wait %.2fs)",
            self.episode.seq, starved, target, rich, starved,
            self.episode.baseline_queue_wait,
        )

    # -- actuation ---------------------------------------------------------

    async def _advance(self) -> None:
        ep = self.episode
        assert ep is not None
        now = self.now_fn()
        if now - ep.started_ts > self.config.episode_timeout_s:
            self._finish("abandoned", "episode timed out")
            return
        try:
            if ep.phase == "drain":
                await self._phase_drain(ep)
            elif ep.phase == "flip":
                await self._phase_flip(ep)
            elif ep.phase == "rejoin":
                await self._phase_rejoin(ep)
            elif ep.phase == "verify":
                self._phase_verify(ep)
            else:  # unknown persisted phase (newer writer?) — bail safely
                self._finish("abandoned", f"unknown phase {ep.phase!r}")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._note_unreachable(ep, e)

    def _note_unreachable(self, ep: Episode, err: Exception) -> None:
        ep.unreachable += 1
        self.last_error = f"{type(err).__name__}: {err}"
        logger.warning(
            "rebalance episode %d: %s unreachable in phase %s (%d/%d): %s",
            ep.seq, ep.engine, ep.phase, ep.unreachable,
            self.config.unreachable_limit, err,
        )
        if ep.unreachable >= self.config.unreachable_limit:
            # the engine died or partitioned mid-episode. Abandoning is
            # safe: drain/flip are idempotent and its restart comes back
            # under the static --pool-role
            self._finish("abandoned",
                         f"engine unreachable x{ep.unreachable}")
        else:
            self._save_state()

    def _transition(self, ep: Episode, phase: str) -> None:
        ep.phase = phase
        ep.phase_ts = self.now_fn()
        ep.unreachable = 0
        self._save_state()
        logger.info("rebalance episode %d: -> %s", ep.seq, phase)

    async def _phase_drain(self, ep: Episode) -> None:
        """POST /drain?wait=true — the existing barrier: admissions stop,
        in-flight streams finish, the engine deregisters. Idempotent, so
        crash-resume lands here harmlessly."""
        import aiohttp

        sess = await self.session_fn()
        timeout = aiohttp.ClientTimeout(
            total=self.config.drain_timeout_s + 10.0
        )
        async with sess.post(
            ep.engine + "/drain", params={"wait": "true"}, timeout=timeout
        ) as resp:
            await resp.read()
            if resp.status == 200:
                self._transition(ep, "flip")
            elif resp.status == 202:
                # barrier not passed yet; re-POST next tick (idempotent)
                ep.unreachable = 0
                self._save_state()
            else:
                raise RuntimeError(f"drain returned HTTP {resp.status}")

    async def _phase_flip(self, ep: Episode) -> None:
        """POST /role — the engine re-opens admissions under the new role
        and re-registers. Idempotent: re-POSTing the same role is a
        no-op flip."""
        sess = await self.session_fn()
        async with sess.post(
            ep.engine + "/role", json={"role": ep.to_role}
        ) as resp:
            await resp.read()
            if resp.status == 200:
                self._transition(ep, "rejoin")
            elif resp.status == 409:
                # the engine is on its SIGTERM way out — not coming back
                self._finish("abandoned", "engine exiting (409 from /role)")
            else:
                raise RuntimeError(f"/role returned HTTP {resp.status}")

    async def _phase_rejoin(self, ep: Episode) -> None:
        """Confirm the engine serves under the new role (GET /health) —
        the explicit re-admission gate before the verify clock starts."""
        sess = await self.session_fn()
        async with sess.get(ep.engine + "/health") as resp:
            body = await resp.json()
            if resp.status != 200:
                # liveness 503 = step loop dead — counts toward the
                # unreachable limit like a refused connection
                raise RuntimeError(f"/health returned HTTP {resp.status}")
            if (not body.get("draining")
                    and body.get("role") == ep.to_role):
                self._transition(ep, "verify")
            elif body.get("role") not in (ep.to_role, None):
                # serving the WRONG role: the flip never landed (engine
                # restarted under its static role mid-episode) — go back
                # one phase rather than verifying a fiction
                self._transition(ep, "flip")
            # else: still draining/settling; retry next tick

    def _phase_verify(self, ep: Episode) -> None:
        """After verify_window_s, judge the flip: a starved-pool queue
        wait WORSE than the episode baseline means the flip hurt — roll
        it back once (re-enter drain with the roles swapped); anything
        else completes the episode."""
        now = self.now_fn()
        if now - ep.phase_ts < self.config.verify_window_s:
            return
        view = self._pool_view()
        current = _max_queue_wait(view.pool(ep.starved_role))
        worse = current > max(ep.baseline_queue_wait,
                              self.config.queue_wait_trigger_s)
        if worse and not ep.rolled_back:
            logger.warning(
                "rebalance episode %d: %s pool queue wait %.2fs > "
                "baseline %.2fs after flip — rolling back",
                ep.seq, ep.starved_role, current, ep.baseline_queue_wait,
            )
            ep.from_role, ep.to_role = ep.to_role, ep.from_role
            ep.rolled_back = True
            self._transition(ep, "drain")
            return
        if ep.rolled_back:
            # the rollback's own verify pass: the engine is back under
            # its original role — close the episode as rolled_back and
            # keep this engine out of the next episodes
            self.engine_cooldown_until[ep.engine] = (
                now + self.config.engine_cooldown_s
            )
            self._finish("rolled_back", "flip made imbalance worse")
        else:
            self._finish("completed", None)

    def _finish(self, outcome: str, reason: str | None) -> None:
        ep = self.episode
        assert ep is not None
        self.flips[outcome] = self.flips.get(outcome, 0) + 1
        self.cooldown_until = self.now_fn() + self.config.cooldown_s
        self.episode = None
        self._imbalance_since = None
        self._save_state()
        logger.info(
            "rebalance episode %d finished: %s%s",
            ep.seq, outcome, f" ({reason})" if reason else "",
        )

    # -- introspection -----------------------------------------------------

    @property
    def phase(self) -> str:
        """The phase gauge's current value: an active episode's phase,
        else cooldown while the global hold-off runs, else observe."""
        if self.episode is not None:
            return self.episode.phase
        if self.now_fn() < self.cooldown_until:
            return "cooldown"
        return "observe"

    def snapshot(self) -> dict:
        """GET /rebalance operator view."""
        now = self.now_fn()
        return {
            "enabled": self.config.enabled,
            "phase": self.phase,
            "episode": self.episode.to_dict() if self.episode else None,
            "episodes_started": self.episodes_started,
            "flips": dict(self.flips),
            "cooldown_remaining_s": max(0.0, self.cooldown_until - now),
            "engine_cooldowns": {
                url: round(until - now, 1)
                for url, until in self.engine_cooldown_until.items()
                if until > now
            },
            "last_error": self.last_error,
            "config": {
                "observe_s": self.config.observe_s,
                "cooldown_s": self.config.cooldown_s,
                "verify_window_s": self.config.verify_window_s,
                "min_prefill": self.config.min_prefill,
                "min_decode": self.config.min_decode,
                "queue_wait_trigger_s": self.config.queue_wait_trigger_s,
                "occupancy_rich_max": self.config.occupancy_rich_max,
            },
        }

    def metrics_lines(self) -> list[str]:
        """Hand-rendered Prometheus lines for the controller's /metrics
        (the live home of these contract names; the router registry
        zero-seeds the same names — check_metrics_contract's exporter
        union)."""
        lines = [f"# TYPE {mc.POOL_REBALANCE_FLIPS} counter"]
        for outcome in mc.POOL_REBALANCE_OUTCOME_VALUES:
            lines.append(
                f'{mc.POOL_REBALANCE_FLIPS}{{outcome="{outcome}"}} '
                f"{self.flips.get(outcome, 0)}"
            )
        lines.append(f"# TYPE {mc.POOL_REBALANCE_PHASE} gauge")
        current = self.phase
        for phase in mc.POOL_REBALANCE_PHASE_VALUES:
            lines.append(
                f'{mc.POOL_REBALANCE_PHASE}{{phase="{phase}"}} '
                f"{1 if phase == current else 0}"
            )
        return lines
