"""Flight recorder, thread-liveness watchdog, and crash postmortems
(docs/37-flight-recorder.md).

Every telemetry layer before this one observes *requests that make
progress*: the tracing spine explains one slow request, the goodput
ledger explains wasted tokens, the saturation meter explains an
under-full chip. A WEDGED engine — a collective that never completes, a
fetcher deadlocked under a tier lock, an XLA compile that never returns
— produces no requests and therefore no evidence. Production engines
(RTP-LLM, PAPERS.md) treat hang diagnosis as a serving-stack feature:
when the process stalls, the process itself should name the stuck thread
and dump what it was doing. Four pieces:

- :class:`FlightRecorder` — a bounded, lock-light ring of structured
  step records appended from the step loop (dispatch/resolve sequence,
  batch shape and phase, scheduler decision summary, queue/pool depths,
  rollback and fault markers). Same noise-floor bar as the StepMeter
  (≤~2% p50, measured by the bench's ``blackbox`` phase). The last N
  records are the black box: what the engine was doing right before it
  stopped doing anything.

- :class:`ThreadRegistry` / :class:`Heartbeat` — every long-lived loop
  in the process beats a heartbeat: the step thread, the hydration
  fetcher, the KV-event publisher, the remote-KV writer, background
  compile jobs. ``beat()`` marks the loop alive-and-busy; ``idle()``
  marks it parked waiting for work (an idle loop is never stale). Ages
  are computed by READERS (exporter, watchdog) from the beat stamps, so
  a dead loop cannot fake freshness.

- :class:`Watchdog` — one daemon thread that turns silence into signal:
  a busy heartbeat older than its threshold, or a device step dispatched
  and never resolved, starts a stall EPISODE — one structured report
  (thread stacks + the last flight records), one counter bump per kind
  (``tpu:engine_step_stalls_total``), one postmortem dump, and /ready
  flips 503 (never /health: restarting a wedged engine is an operator
  decision, not a kubelet reflex) until the stall clears.

- :func:`write_postmortem` / :class:`PostmortemDumper` — a redacted JSON
  black box (flight ring, heartbeat table, thread stacks, config
  fingerprint, timing/hydration snapshots, env) written to
  ``--postmortem-dir`` on watchdog trip, SIGQUIT, and fatal step-thread
  exceptions; served live at ``GET /debug/flight`` and on demand via
  ``POST /debug/postmortem``. bench.py's preflight watchdog writes the
  same artifact, so the r04/r05 chip wedge finally leaves a file behind.

:class:`EventLoopLagProbe` rides along for the asyncio processes (router
and KV controller): a starved event loop serves nothing while every
request-vantage metric just goes quiet —
``tpu:router_event_loop_lag_seconds`` is the decaying peak of how far a
short sleep overshot its deadline.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import re
import sys
import threading
import time
import traceback
from collections import deque

from .. import metrics_contract as mc
from ..utils.logging import init_logger

logger = init_logger(__name__)

# closed label sets — the contract owns them; this module records against
# them (a thread name outside THREAD_NAMES raises at registration, so the
# exporter's seeded cardinality can never be exceeded)
THREAD_NAMES = mc.THREAD_NAME_VALUES
STALL_KINDS = mc.STALL_KIND_VALUES

DEFAULT_CAPACITY = 512
DEFAULT_WATCH_INTERVAL_S = 1.0
DEFAULT_STALL_AFTER_S = 120.0
# background XLA compiles legitimately run for minutes (plus up to the
# 10-minute idle gate); only a compile past this is the "compiles
# forever" wedge
DEFAULT_BG_COMPILE_STALL_S = 900.0


class FlightRecorder:
    """Bounded ring of structured step records (the black box).

    Appended from the step thread; snapshotted by the watchdog, the
    postmortem dumper, and GET /debug/flight. One small lock guards the
    ring (an append is a dict build + deque append — microseconds against
    a millisecond-scale step). The dispatch/resolve cursor is tracked
    even when recording is disabled: the watchdog's unresolved-step
    detection must survive ``--flight-recording false``.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.enabled = enabled
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._seq = 0
        # the ONE outstanding dispatched-but-unresolved device step (the
        # pipeline is two-deep: at most one step is in flight between
        # step() calls). (seq, monotonic dispatch time, kind) or None.
        self._outstanding: tuple[int, float, str] | None = None
        self.records_total = 0

    # -- step-loop recording (step thread) ---------------------------------

    def _append(self, event: str, fields: dict) -> None:
        if not self.enabled:
            return
        fields["event"] = event
        fields["t"] = time.time()
        with self._lock:
            self._ring.append(fields)
            self.records_total += 1

    def dispatch(
        self, kind: str, rows: int, tokens: int,
        waiting: int = 0, running: int = 0, pool_usage: float = 0.0,
        window: int = 0,
    ) -> int:
        """One device dispatch (decode window / verify / prefill chunk).
        Returns the dispatch seq the matching resolve()/discard() names."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._outstanding = (seq, time.monotonic(), kind)
        self._append("dispatch", {
            "seq": seq, "kind": kind, "rows": rows, "tokens": tokens,
            "window": window, "waiting": waiting, "running": running,
            "pool_usage": round(pool_usage, 4),
        })
        return seq

    def resolve(self, seq: int, accepted: int = 0) -> None:
        """The dispatch's results were synced to the host — the step is no
        longer a stall candidate."""
        with self._lock:
            if self._outstanding is not None and self._outstanding[0] <= seq:
                self._outstanding = None
        self._append("resolve", {"seq": seq, "accepted": accepted})

    def discard(self, seq: int) -> None:
        """A dispatched pipeline step was rolled back (speculation
        invalidated) — discarded work is resolved work for liveness."""
        with self._lock:
            if self._outstanding is not None and self._outstanding[0] <= seq:
                self._outstanding = None
        self._append("rollback", {"seq": seq})

    def fault(self, message: str) -> None:
        """A step-loop exception (transient or fatal)."""
        with self._lock:
            self._outstanding = None  # the step loop abandoned it
        self._append("fault", {"message": str(message)[:500]})

    def note(self, event: str, **fields) -> None:
        """Off-hot-path markers (watchdog stall/recovery, drain, ...)."""
        self._append(event, dict(fields))

    # -- reading (watchdog / exporter / debug) -----------------------------

    def outstanding_age_s(self) -> tuple[float, str] | None:
        """(seconds since dispatch, kind) of the unresolved device step,
        or None when nothing is in flight."""
        with self._lock:
            out = self._outstanding
        if out is None:
            return None
        return time.monotonic() - out[1], out[2]

    def snapshot(self, last: int | None = None) -> list[dict]:
        with self._lock:
            records = list(self._ring)
        if last is not None:
            records = records[-last:]
        return records


class Heartbeat:
    """One long-lived loop's liveness stamp. ``beat()`` = alive and busy;
    ``idle()`` = parked waiting for work (never stale). Readers compute
    age from the stamps — the loop itself never reports an age."""

    __slots__ = ("name", "stall_after_s", "explicit_threshold",
                 "_last_beat", "_busy", "beats")

    def __init__(self, name: str, stall_after_s: float,
                 explicit_threshold: bool = True):
        self.name = name
        self.stall_after_s = stall_after_s
        # loops registered WITHOUT their own threshold follow the
        # registry default (the --watchdog-stall-s knob); explicit ones
        # (bg_compile's generous compile budget, the publisher's
        # interval-derived bound) keep theirs
        self.explicit_threshold = explicit_threshold
        self._last_beat = time.monotonic()
        self._busy = False
        self.beats = 0

    def beat(self) -> None:
        self._last_beat = time.monotonic()
        self._busy = True
        self.beats += 1

    def idle(self) -> None:
        self._last_beat = time.monotonic()
        self._busy = False

    def age_s(self) -> float:
        return time.monotonic() - self._last_beat

    @property
    def busy(self) -> bool:
        return self._busy

    def stale(self) -> bool:
        return self._busy and self.age_s() > self.stall_after_s

    def snapshot(self) -> dict:
        return {
            "thread": self.name,
            "age_s": round(self.age_s(), 3),
            "busy": self._busy,
            "stall_after_s": self.stall_after_s,
            "beats": self.beats,
            "stale": self.stale(),
        }


class ThreadRegistry:
    """Where every long-lived loop registers and beats. Names come from
    the CLOSED contract set (metrics_contract.THREAD_NAME_VALUES) so the
    heartbeat-age gauge's cardinality is bounded by construction — an
    unknown name raises at registration, not at scrape."""

    def __init__(self, default_stall_after_s: float = DEFAULT_STALL_AFTER_S):
        self._lock = threading.Lock()
        self._beats: dict[str, Heartbeat] = {}
        self.default_stall_after_s = default_stall_after_s

    def register(
        self, name: str, stall_after_s: float | None = None
    ) -> Heartbeat:
        """Idempotent: re-registering a name (restartable loops) refreshes
        the existing heartbeat rather than minting a second one.
        ``stall_after_s=None`` follows the registry default (the
        --watchdog-stall-s knob, adjustable after registration via
        :meth:`set_default_stall_after_s`)."""
        if name not in THREAD_NAMES:
            raise ValueError(
                f"thread name {name!r} is not in the closed contract set "
                f"{THREAD_NAMES}"
            )
        explicit = stall_after_s is not None
        threshold = (
            stall_after_s if explicit else self.default_stall_after_s
        )
        with self._lock:
            hb = self._beats.get(name)
            if hb is None:
                hb = self._beats[name] = Heartbeat(
                    name, threshold, explicit_threshold=explicit
                )
            else:
                hb.stall_after_s = threshold
                hb.explicit_threshold = explicit
                hb.idle()  # a restarting loop starts fresh, not stale
            return hb

    def set_default_stall_after_s(self, stall_after_s: float) -> None:
        """Apply a new default threshold (the --watchdog-stall-s knob,
        parsed AFTER the engine registered its loops) to every heartbeat
        that did not pick its own."""
        with self._lock:
            self.default_stall_after_s = stall_after_s
            for hb in self._beats.values():
                if not hb.explicit_threshold:
                    hb.stall_after_s = stall_after_s

    def unregister(self, name: str) -> None:
        """A loop that stops ON PURPOSE (drain stopped the publisher)
        leaves the table — a deliberate stop must not read as a wedge."""
        with self._lock:
            self._beats.pop(name, None)

    def ages(self) -> dict[str, float]:
        """thread → seconds since last beat, for every registered loop."""
        with self._lock:
            beats = list(self._beats.values())
        return {hb.name: hb.age_s() for hb in beats}

    def stale(self) -> list[Heartbeat]:
        with self._lock:
            beats = list(self._beats.values())
        return [hb for hb in beats if hb.stale()]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            beats = list(self._beats.values())
        return {hb.name: hb.snapshot() for hb in beats}


def thread_stacks() -> dict[str, list[str]]:
    """Current stack of every live thread, keyed by thread name — the
    faulthandler view as capturable strings (faulthandler itself can only
    write to a real file descriptor)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks: dict[str, list[str]] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        stacks[name] = [
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        ]
    return stacks


# -- postmortem dumps --------------------------------------------------------

# keys whose VALUES are secrets wherever they appear in a postmortem doc
# (tenant api keys, subscriber bearer keys, HF tokens, auth headers)
_REDACT_KEY_RE = re.compile(
    r"(api[-_]?key|authorization|auth|token|secret|password|bearer|"
    r"credential)", re.IGNORECASE,
)
# env vars worth carrying in a wedge postmortem (values of matching
# _REDACT_KEY_RE names are redacted even here)
_ENV_PREFIXES = (
    "JAX_", "TPU_", "XLA_", "LIBTPU", "KV_", "POD_", "ENGINE_",
    "PREFLIGHT_",
)


def redact(obj):
    """Recursively mask values under secret-shaped keys. Applied to the
    WHOLE postmortem doc right before serialization, so no section can
    leak a tenant key by forgetting to scrub its own fields."""
    if isinstance(obj, dict):
        return {
            k: ("[redacted]" if isinstance(k, str) and _REDACT_KEY_RE.search(k)
                else redact(v))
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    return obj


# disambiguates same-second dump filenames within one process
_DUMP_COUNTER = itertools.count()


def _captured_env() -> dict[str, str]:
    return {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES)
    }


def build_postmortem(
    trigger: str,
    reason: str,
    recorder: FlightRecorder | None = None,
    registry: ThreadRegistry | None = None,
    sections: dict | None = None,
) -> dict:
    """The redacted black-box document. `sections` carries caller-provided
    context (config fingerprint, /debug/timing + /debug/hydration
    snapshots, watchdog state) — everything is redacted together."""
    doc: dict = {
        "postmortem": True,
        "trigger": trigger,
        "reason": reason,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "pid": os.getpid(),
        "threads": thread_stacks(),
        "env": _captured_env(),
    }
    if recorder is not None:
        doc["flight"] = recorder.snapshot()
        out = recorder.outstanding_age_s()
        if out is not None:
            doc["outstanding_step"] = {
                "age_s": round(out[0], 3), "kind": out[1],
            }
    if registry is not None:
        doc["heartbeats"] = registry.snapshot()
    for key, value in (sections or {}).items():
        doc[key] = value
    return redact(doc)


def write_postmortem(
    out_dir: str,
    trigger: str,
    reason: str,
    recorder: FlightRecorder | None = None,
    registry: ThreadRegistry | None = None,
    sections: dict | None = None,
) -> tuple[str, dict]:
    """Build + write one postmortem JSON file; returns (path, doc).
    Filenames carry the trigger and a wall timestamp so repeated wedges
    never overwrite each other."""
    doc = build_postmortem(trigger, reason, recorder, registry, sections)
    os.makedirs(out_dir, exist_ok=True)
    # pid + a process-wide monotonic counter: two dumps landing in the
    # same SECOND (a watchdog episode racing a SIGQUIT, two wedges in one
    # bench run) must not overwrite each other's evidence
    fname = "postmortem-{}-{}-{}-{}.json".format(
        trigger, time.strftime("%Y%m%dT%H%M%S"), os.getpid(),
        next(_DUMP_COUNTER),
    )
    path = os.path.join(out_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)  # a reader never sees a torn dump
    return path, doc


class PostmortemDumper:
    """The engine server's dump trigger: one place that knows the output
    dir, the recorder/registry, and the extra context callables (config,
    timing, hydration). ``out_dir=''`` disables files — build() still
    serves POST /debug/postmortem inline."""

    def __init__(
        self,
        out_dir: str = "",
        recorder: FlightRecorder | None = None,
        registry: ThreadRegistry | None = None,
        context_fn=None,
    ):
        self.out_dir = out_dir
        self.recorder = recorder
        self.registry = registry
        # zero-arg callable -> dict of extra sections, evaluated at dump
        # time (config fingerprint, timing, hydration snapshots)
        self.context_fn = context_fn
        self.dumps_written = 0
        self.last_path: str | None = None

    def _sections(self) -> dict:
        if self.context_fn is None:
            return {}
        try:
            return dict(self.context_fn())
        except Exception as e:  # a broken context must not lose the dump
            return {"context_error": f"{type(e).__name__}: {e}"}

    def build(self, trigger: str, reason: str) -> dict:
        return build_postmortem(
            trigger, reason, self.recorder, self.registry, self._sections()
        )

    def dump(self, trigger: str, reason: str) -> tuple[str | None, dict]:
        """Write (when a dir is configured) and return (path, doc). Never
        raises: the dumper runs on dying threads and signal handlers."""
        try:
            if not self.out_dir:
                return None, self.build(trigger, reason)
            path, doc = write_postmortem(
                self.out_dir, trigger, reason,
                self.recorder, self.registry, self._sections(),
            )
            self.dumps_written += 1
            self.last_path = path
            logger.error("postmortem (%s) written to %s", trigger, path)
            return path, doc
        except Exception:
            logger.exception("postmortem dump (%s) failed", trigger)
            return None, {"postmortem": False, "trigger": trigger}


class Watchdog:
    """The thread that turns silence into signal.

    Every ``interval_s`` it checks (a) each registered heartbeat's
    staleness and (b) the flight recorder's outstanding device step. A
    transition from clear to stalled starts one EPISODE: one structured
    stall report in the log (stacks + last flight records), one counter
    bump per kind, one ``on_stall`` callback (the server hooks the
    postmortem dumper there). While stalled, ``stalled`` is the live
    report the /ready handler 503s with — liveness (/health) is NEVER
    flipped: k8s restarting a wedged engine destroys the evidence this
    module exists to capture, and the operator may prefer a /debug/flight
    look first.
    """

    def __init__(
        self,
        registry: ThreadRegistry,
        recorder: FlightRecorder | None = None,
        interval_s: float = DEFAULT_WATCH_INTERVAL_S,
        stall_after_s: float = DEFAULT_STALL_AFTER_S,
        on_stall=None,
    ):
        self.registry = registry
        self.recorder = recorder
        self.interval_s = interval_s
        # default threshold for the unresolved-step check (heartbeats
        # carry their own per-loop thresholds)
        self.stall_after_s = stall_after_s
        self.on_stall = on_stall  # callable(report: dict), once per episode
        self.stall_counts: dict[str, int] = {k: 0 for k in STALL_KINDS}
        self.stall_episodes = 0
        self.stalled: dict | None = None  # live report while stalled
        self._hb = registry.register(
            "watchdog", stall_after_s=max(10.0, 10 * interval_s)
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # threads/kinds already counted in the CURRENT episode — a wedge
        # that persists for minutes is one trip per (kind, thread), not
        # one per check round
        self._episode_keys: set[tuple[str, str]] = set()

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="engine-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None
        self.registry.unregister("watchdog")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._hb.beat()
                self.check()
            except Exception:  # the monitor must outlive its own bugs
                logger.exception("watchdog check failed")

    def check(self) -> dict | None:
        """One detection round (factored out so tests drive it without
        the thread). Returns the current stall report or None."""
        findings: list[dict] = []
        for hb in self.registry.stale():
            if hb.name == "watchdog":
                continue  # self-staleness is for the exporter to surface
            findings.append({
                "kind": "stale_heartbeat",
                "thread": hb.name,
                "age_s": round(hb.age_s(), 3),
                "stall_after_s": hb.stall_after_s,
            })
        if self.recorder is not None:
            out = self.recorder.outstanding_age_s()
            if out is not None and out[0] > self.stall_after_s:
                findings.append({
                    "kind": "unresolved_step",
                    "thread": "step",
                    "age_s": round(out[0], 3),
                    "dispatch_kind": out[1],
                    "stall_after_s": self.stall_after_s,
                })
        if not findings:
            if self.stalled is not None:
                logger.warning(
                    "watchdog: stall cleared after %d finding(s)",
                    len(self._episode_keys),
                )
                if self.recorder is not None:
                    self.recorder.note("stall_cleared")
            self.stalled = None
            self._episode_keys.clear()
            return None
        new = [
            f for f in findings
            if (f["kind"], f["thread"]) not in self._episode_keys
        ]
        self.stalled = {
            "since": self.stalled["since"] if self.stalled else time.time(),
            "findings": findings,
        }
        if new:
            if not self._episode_keys:
                self.stall_episodes += 1
            for f in new:
                self._episode_keys.add((f["kind"], f["thread"]))
                self.stall_counts[f["kind"]] += 1
            self._report(new)
        return self.stalled

    def _report(self, findings: list[dict]) -> None:
        """ONE structured stall report per new finding set: the named
        threads, their stacks, and the last flight records — the log line
        an operator greps for when the bench goes dark."""
        names = ", ".join(
            f"{f['thread']} ({f['kind']}, {f['age_s']:.1f}s)"
            for f in findings
        )
        stacks = thread_stacks()
        tail = (
            self.recorder.snapshot(last=16)
            if self.recorder is not None else []
        )
        logger.error(
            "watchdog: engine stalled — %s\nstall report: %s",
            names,
            json.dumps(redact({
                "findings": findings,
                "heartbeats": self.registry.snapshot(),
                "threads": stacks,
                "flight_tail": tail,
            }), indent=1),
        )
        if self.recorder is not None:
            self.recorder.note("stall", findings=findings)
        if self.on_stall is not None:
            try:
                self.on_stall({"findings": findings})
            except Exception:
                logger.exception("watchdog on_stall callback failed")

    def snapshot(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "stall_after_s": self.stall_after_s,
            "stalled": self.stalled,
            "episodes": self.stall_episodes,
            "counts": dict(self.stall_counts),
        }


class EventLoopLagProbe:
    """Asyncio event-loop starvation probe (router / KV controller).

    Sleeps ``interval_s`` in a loop and measures how far each wakeup
    overshot its deadline; ``lag_s`` is a decaying peak (τ ~30s), so a
    scrape every 15s still sees a one-off 2s stall instead of whatever
    the last healthy tick read. A loop blocked OUTSIDE await (sync I/O,
    a giant json.loads — the tpulint async-blocking bug class, live) is
    exactly what inflates it."""

    _DECAY_TAU_S = 30.0

    def __init__(self, interval_s: float = 0.5):
        self.interval_s = interval_s
        self.last_lag_s = 0.0
        self.lag_s = 0.0  # decaying peak — the exported gauge
        self.ticks = 0
        self._task = None
        self._peak_t = time.monotonic()

    def _observe(self, lag: float) -> None:
        now = time.monotonic()
        decayed = self.lag_s * math.exp(
            -(now - self._peak_t) / self._DECAY_TAU_S
        )
        self.last_lag_s = lag
        self.lag_s = max(lag, decayed)
        self._peak_t = now
        self.ticks += 1

    async def _run(self) -> None:
        import asyncio

        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval_s)
            self._observe(max(0.0, time.monotonic() - t0 - self.interval_s))

    def start(self) -> None:
        import asyncio

        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        import asyncio

        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def snapshot(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "lag_s": round(self.lag_s, 6),
            "last_lag_s": round(self.last_lag_s, 6),
            "ticks": self.ticks,
        }
