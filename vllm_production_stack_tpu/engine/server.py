"""OpenAI-compatible HTTP server for the TPU engine (aiohttp.web).

Implements the exact surface the reference stack's router and operator expect
from an engine pod (SURVEY §7.1): /v1/chat/completions, /v1/completions,
/v1/models, /metrics, /health, /sleep, /wake_up, /is_sleeping,
/v1/load_lora_adapter, /v1/unload_lora_adapter, /tokenize, /detokenize,
/version (main_router.py:50-246; service_discovery.py model scrape;
loraadapter_controller.go:582-611).

Run: python -m vllm_production_stack_tpu.engine.server --model tiny-llama
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import numpy as np
from aiohttp import web
from pydantic import ValidationError

from .. import __version__
from ..fleet import SessionStickinessAudit
from ..models.registry import resolve_model_config
from ..qos import tenant_from_headers
from ..utils.logging import init_logger
from .async_engine import AsyncEngine, EngineDrainingError, EngineSleepingError
from .config import (
    CacheConfig,
    EngineConfig,
    LoRAConfig,
    ParallelConfig,
    SchedulerConfig,
)
from .engine import (
    DeadlineExceededError,
    EngineOverloadedError,
    LLMEngine,
)
from ..tracing import TraceStore, mono_to_epoch
from .flightrec import PostmortemDumper, Watchdog
from .kv_peer import MAX_PEER_RUN_BLOCKS, peer_hint_from_headers
from .metrics import EngineMetrics, OPENMETRICS_CONTENT_TYPE, wants_openmetrics
from .protocol import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    ErrorResponse,
    ModelCard,
    ModelList,
    RerankRequest,
    ScoreRequest,
    random_id,
    usage,
)

logger = init_logger(__name__)

DEFAULT_MAX_TOKENS = 256
# n (parallel sampling) cap: each choice is its own engine request (the
# prefix cache dedups the shared prompt), so the cost model is the same as
# the scheduler's per-request admission — the cap just bounds fan-out
MAX_N_CHOICES = 8


def error(status: int, message: str, type_: str = "invalid_request_error",
          headers: dict | None = None):
    return web.json_response(
        ErrorResponse(message=message, type=type_, code=status).model_dump(),
        status=status,
        headers=headers,
    )


# relative time budget in ms, carried router → engine (clock-skew safe: the
# router injects/forwards it, each hop converts to its own monotonic clock)
DEADLINE_HEADER = "x-request-deadline-ms"


def deadline_from_headers(headers) -> float | None:
    """Absolute time.monotonic() deadline from the x-request-deadline-ms
    header, or None. Malformed values are ignored (a bad client header must
    not 500 the request — the deadline is an optimization, not input)."""
    raw = headers.get(DEADLINE_HEADER)
    if not raw:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        return None
    if ms <= 0:
        return None
    return time.monotonic() + ms / 1000.0


def _kv_subscriber_urls() -> list[str]:
    """KV_CONTROLLER_URL parsed as a comma-separated subscriber list: the
    KV controller, embedded-index router replicas, or any mix — the KV
    event publisher fans batches out to all of them and registration runs
    against each (docs/34-fleet-routing.md)."""
    import os

    raw = os.environ.get("KV_CONTROLLER_URL") or ""
    return [u.strip() for u in raw.split(",") if u.strip()]


class _StreamUnsupported(Exception):
    """Sender has no /kv/export_stream (older engine) — use the npz hop."""


class EngineServer:
    def __init__(self, engine: LLMEngine, served_model_name: str | None = None,
                 drain_timeout_s: float = 30.0, request_tracing: bool = True,
                 trace_buffer: int = 256, watchdog: bool = True,
                 watchdog_interval_s: float = 1.0,
                 watchdog_stall_s: float = 120.0, postmortem_dir: str = "",
                 pool_role: str = ""):
        self.engine = engine
        self.async_engine = AsyncEngine(engine)
        self.model_name = served_model_name or engine.config.model.model
        self.metrics = EngineMetrics(self.model_name)
        # disaggregated pool role (docs/40-pool-rebalancing.md): a RUNTIME
        # property, seeded from --pool-role and flipped by POST /role. The
        # engine is the authority — it advertises the role on /metrics
        # (tpu:pool_role), /health, and controller registration; the
        # router and rebalancer FOLLOW it. "" = not in a disaggregated
        # deployment.
        self.pool_role: str | None = pool_role or None
        self.metrics.set_pool_role(self.pool_role)
        # serializes POST /role flips against each other and the drain path
        self._role_lock = asyncio.Lock()
        # request-tracing spine (docs/28-request-tracing.md): per-request
        # span timelines joined to the router's trace via the inbound
        # traceparent header, served by /debug/requests. Disabled
        # (--request-tracing false) it degrades to the NULL_TRACE no-op
        # path; the tpu:request_* histograms are observed either way.
        self.traces = TraceStore(
            capacity=trace_buffer, enabled=request_tracing,
            service="tpu-engine",
        )
        # on-demand xprof capture (/debug/profile/start|stop): the dir of
        # the live jax.profiler trace, None when not capturing
        self._profile_dir: str | None = None
        self._session = None  # lazy outbound ClientSession (kv_pull)
        self.kv_event_publisher = None  # started when KV_CONTROLLER_URL set
        self._tok_repr_cache: dict[int, tuple[str, list[int]]] = {}
        self._start_time = time.time()
        # graceful drain (SIGTERM / POST /drain): admissions stop, in-flight
        # streams finish (bounded by drain_timeout_s), the KV event log is
        # flushed and the engine deregisters from its controller
        self.drain_timeout_s = drain_timeout_s
        self._drain_task: asyncio.Task | None = None
        self._exit_task: asyncio.Task | None = None
        self._drained = asyncio.Event()
        # OpenAI system_fingerprint: identifies the serving configuration
        # whose outputs a seed reproduces — our model fingerprint (weights
        # + seed + kv dtype) is exactly that identity
        self.system_fingerprint = "fp_" + engine.model_fingerprint[:12]
        # session-stickiness audit (docs/32-fleet-telemetry.md): counts
        # consistent-hash affinity breaks from the router-stamped
        # x-session-sticky-* headers. self_url (the same POD_IP:ENGINE_PORT
        # identity the KV event publisher advertises) arms the
        # non_owner_delivery detection; without it owner_changed still works.
        self.stickiness = SessionStickinessAudit(
            self_url=self._advertised_url()
        )
        # flight recorder / watchdog / postmortems (docs/37-flight-
        # recorder.md): the dumper writes the redacted black box on
        # watchdog trip, SIGQUIT, fatal step-thread death, and POST
        # /debug/postmortem; the watchdog turns heartbeat silence and
        # never-resolved dispatches into a named stall that flips /ready
        # (never /health — restarting a wedged engine destroys the
        # evidence this layer exists to capture)
        # the knob is parsed after the engine registered its loops —
        # non-explicit heartbeats (step, fetcher, writer) follow it
        engine.threads.set_default_stall_after_s(watchdog_stall_s)
        self.postmortems = PostmortemDumper(
            out_dir=postmortem_dir,
            recorder=engine.flightrec,
            registry=engine.threads,
            context_fn=self._postmortem_context,
        )
        self.watchdog: Watchdog | None = None
        if watchdog:
            self.watchdog = Watchdog(
                engine.threads,
                recorder=engine.flightrec,
                interval_s=watchdog_interval_s,
                stall_after_s=watchdog_stall_s,
                on_stall=lambda report: self.postmortems.dump(
                    "watchdog", json.dumps(report.get("findings", []))
                ),
            )
        # a fatally wedged step loop dumps its own black box on the way
        # out — the dying thread's stack is the one that matters
        self.async_engine.on_fatal = lambda e: self.postmortems.dump(
            "fatal_step_error", f"{type(e).__name__}: {e}"
        )

    @staticmethod
    def _advertised_url() -> str | None:
        """This engine's cluster-visible base URL (http://POD_IP:ENGINE_PORT
        — the identity used for KV controller registration), or None
        outside a deployment that sets the downward-API env."""
        import os

        pod_ip = os.environ.get("POD_IP")
        if not pod_ip:
            return None
        return f"http://{pod_ip}:{os.environ.get('ENGINE_PORT', '8000')}"

    @property
    def lora_adapters(self) -> dict[str, str]:
        """Loaded adapters (name → path). The ENGINE is the single registry —
        a server-side mirror desyncs the moment a load/unload half-fails."""
        return self.engine.lora_adapters

    # -- app wiring --------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        r = app.router
        r.add_post("/v1/chat/completions", self.chat_completions)
        r.add_post("/v1/completions", self.completions)
        r.add_post("/v1/embeddings", self.embeddings)
        r.add_post("/v1/score", self.score)
        r.add_post("/v1/rerank", self.rerank)
        r.add_get("/v1/models", self.list_models)
        r.add_get("/health", self.health)
        r.add_get("/ready", self.ready)
        r.add_post("/drain", self.drain)
        r.add_post("/role", self.set_role)
        r.add_get("/metrics", self.metrics_endpoint)
        r.add_get("/debug", self.debug_index)
        r.add_get("/debug/timing", self.debug_timing)
        r.add_get("/debug/hydration", self.debug_hydration)
        r.add_get("/debug/requests", self.debug_requests)
        r.add_get("/debug/flight", self.debug_flight)
        r.add_get("/debug/programs", self.debug_programs)
        r.add_post("/debug/postmortem", self.debug_postmortem)
        r.add_post("/debug/profile/start", self.debug_profile_start)
        r.add_post("/debug/profile/stop", self.debug_profile_stop)
        r.add_post("/sleep", self.sleep)
        r.add_post("/wake_up", self.wake_up)
        r.add_get("/is_sleeping", self.is_sleeping)
        r.add_post("/v1/load_lora_adapter", self.load_lora_adapter)
        r.add_post("/v1/unload_lora_adapter", self.unload_lora_adapter)
        r.add_post("/kv/lookup", self.kv_lookup)
        r.add_post("/kv/peer_contains", self.kv_peer_contains)
        r.add_post("/kv/peer_fetch", self.kv_peer_fetch)
        r.add_post("/kv/peer_device_pull", self.kv_peer_device_pull)
        r.add_post("/kv/peer_replicate", self.kv_peer_replicate)
        r.add_post("/kv/replicated", self.kv_replicated)
        r.add_post("/kv/export", self.kv_export)
        r.add_post("/kv/export_stream", self.kv_export_stream)
        r.add_post("/kv/import", self.kv_import)
        r.add_post("/kv/pull", self.kv_pull)
        r.add_post("/tokenize", self.tokenize)
        r.add_post("/detokenize", self.detokenize)
        r.add_get("/version", self.version)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app: web.Application) -> None:
        self.async_engine.start(asyncio.get_running_loop())
        await self._register_with_kv_controller("/register")
        self._start_kv_event_publisher()
        self._install_signal_handlers()
        if self.watchdog is not None:
            self.watchdog.start()

    def _install_signal_handlers(self) -> None:
        """SIGTERM = graceful drain, then exit (k8s pod termination: preStop
        POSTs /drain first, the kubelet's SIGTERM follows; a bare SIGTERM
        without preStop gets the same drain). Replaces aiohttp's default
        immediate-GracefulExit handler; no-op where signals aren't available
        (non-main thread — the aiohttp TestServer harness)."""
        import signal

        try:
            loop = asyncio.get_running_loop()
            loop.add_signal_handler(
                signal.SIGTERM, self._begin_drain, True
            )
        except (NotImplementedError, RuntimeError, ValueError):
            pass
        # SIGQUIT = dump a postmortem and KEEP RUNNING (replacing the
        # default core-dump-and-die): the operator's "what is this engine
        # doing right now" signal, file-shaped instead of stderr-shaped.
        # The dump walks every thread stack and writes a file, so it runs
        # in the executor — blocking the event loop with it would stall
        # every in-flight stream and inflate the very liveness signals
        # being debugged (same discipline as POST /debug/postmortem).
        try:
            loop = asyncio.get_running_loop()
            loop.add_signal_handler(
                signal.SIGQUIT,
                lambda: loop.run_in_executor(
                    None, self.postmortems.dump, "sigquit",
                    "operator SIGQUIT",
                ),
            )
        except (NotImplementedError, RuntimeError, ValueError, AttributeError):
            pass

    def _start_kv_event_publisher(self) -> None:
        """Push-based cluster KV index: publish this pool's KV events to
        every subscriber named by KV_CONTROLLER_URL (comma-separated — the
        KV controller, embedded-index router replicas, or both) so /lookup
        never has to probe this engine per request (engine/kv_events.py).
        Each subscriber keeps its own cursor/resync state, so a cold router
        replica heals through its own snapshot while the rest stream
        batches (docs/34-fleet-routing.md)."""
        import os

        subscribers = _kv_subscriber_urls()
        pod_ip = os.environ.get("POD_IP")
        pool = self.engine.scheduler.pool
        if not subscribers or not pod_ip or pool.events is None:
            return
        from .kv_events import DEFAULT_FLUSH_INTERVAL_S, KVEventPublisher

        port = os.environ.get("ENGINE_PORT", "8000")
        interval_s = float(
            os.environ.get("KV_EVENTS_FLUSH_S", DEFAULT_FLUSH_INTERVAL_S)
        )
        self.kv_event_publisher = KVEventPublisher(
            subscribers,
            f"http://{pod_ip}:{port}",
            pool.events,
            self.async_engine.kv_events_snapshot,
            pool.block_size,
            self._client_session,
            interval_s=interval_s,
            headers=self._kv_controller_headers(),
            # liveness: one beat per publish round; the threshold rides
            # well above the per-POST send timeout so one slow subscriber
            # round isn't a wedge, a HELD one is
            heartbeat=self.engine.threads.register(
                "kv_event_publisher",
                stall_after_s=max(30.0, 20 * interval_s),
            ),
        )
        self.kv_event_publisher.start()
        logger.info("KV event publisher -> %s (flush every %.2fs)",
                    ", ".join(subscribers),
                    self.kv_event_publisher.interval_s)

    @staticmethod
    def _kv_controller_headers() -> dict:
        """Bearer key for a keyed KV-event subscriber (a router running with
        --api-key protects /kv/events and /register|/deregister)."""
        import os

        key = os.environ.get("KV_CONTROLLER_API_KEY")
        return {"Authorization": f"Bearer {key}"} if key else {}

    async def _register_with_kv_controller(self, endpoint: str) -> None:
        """Join/leave every KV subscriber's engine set when deployed with
        KV_CONTROLLER_URL (comma-separated; +POD_IP/ENGINE_PORT from the
        operator's downward API) — the LMCACHE_CONTROLLER_URL contract
        (deployment-vllm-multi.yaml:324-339), fanned out so embedded-index
        router replicas see the same membership the controller does."""
        import os

        subscribers = _kv_subscriber_urls()
        pod_ip = os.environ.get("POD_IP")
        if not subscribers or not pod_ip:
            return
        port = os.environ.get("ENGINE_PORT", "8000")
        my_url = f"http://{pod_ip}:{port}"

        body: dict = {"url": my_url}
        if self.pool_role:
            # the live pool role rides registration so the controller's
            # rebalancer sees membership per pool without a scrape hop
            body["role"] = self.pool_role
        identity = self._device_identity()
        if identity is not None:
            # mesh/process-group identity rides the registration so
            # /peer_lookup replies can negotiate the device-path peer
            # transport per (requester, owner) pair (docs/39)
            body["transport"] = identity

        async def post_one(controller: str) -> None:
            try:
                async with self._client_session().post(
                    controller.rstrip("/") + endpoint, json=body,
                    headers=self._kv_controller_headers(),
                ) as resp:
                    logger.info(
                        "KV controller %s%s (%s): HTTP %d",
                        controller, endpoint, my_url, resp.status,
                    )
            except Exception as e:
                logger.warning("KV controller %s failed: %s", endpoint, e)

        # concurrent, not sequential: one unreachable subscriber must not
        # delay registration with (or, worse, shutdown deregistration
        # from) the healthy ones by its full connect timeout
        await asyncio.gather(*(post_one(c) for c in subscribers))

    async def _on_cleanup(self, app: web.Application) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.kv_event_publisher is not None:
            await self.kv_event_publisher.stop()
            self.engine.threads.unregister("kv_event_publisher")
        await self._register_with_kv_controller("/deregister")
        self.async_engine.shutdown()
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def _client_session(self):
        """Long-lived outbound session (KV pulls are on the PD hot path —
        per-request session churn taxes latency and file descriptors)."""
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30)
            )
        return self._session

    # -- inference routes --------------------------------------------------

    @staticmethod
    def _admission_error(e: Exception) -> web.Response | None:
        """Map lifecycle-gate exceptions to their HTTP shape: overload →
        429 + Retry-After (from observed decode throughput), expired/
        unmeetable deadline → 503, draining → 503 + X-Engine-Draining (the
        router fails over on that header instead of surfacing it)."""
        if isinstance(e, EngineOverloadedError):
            import math

            return error(
                429, str(e), "overloaded",
                headers={"Retry-After": str(int(math.ceil(e.retry_after_s)))},
            )
        if isinstance(e, DeadlineExceededError):
            return error(503, str(e), "deadline_exceeded")
        if isinstance(e, EngineDrainingError):
            return error(
                503, str(e), "service_unavailable",
                headers={"X-Engine-Draining": "1"},
            )
        return None

    def _gate_admission(self, request):
        """(deadline, tenant, refusal) for one inference request — run
        BEFORE any SSE headers go out so 429/503 keep their status codes.
        The same checks rerun at submit time (this is the fast path, not
        the only line of defense). The tenant context comes from the
        router-stamped x-tenant-id / x-priority / x-tenant-weight headers
        (qos.tenant_from_headers); unstamped traffic is the default
        tenant, and a higher-priority class can pass a full queue by
        evicting lower-priority waiting work (lowest-priority-first
        shedding, claimed at submit time)."""
        deadline = deadline_from_headers(request.headers)
        tenant = tenant_from_headers(request.headers)
        # stickiness audit (docs/32-fleet-telemetry.md): every inference
        # request carrying a router session stamp is observed, refused or
        # not — an affinity break on a request the engine then sheds is
        # still an affinity break
        self.stickiness.observe_headers(request.headers)
        try:
            self.async_engine.precheck_admission(deadline, tenant=tenant)
        except (EngineOverloadedError, DeadlineExceededError,
                EngineDrainingError) as e:
            return deadline, tenant, self._admission_error(e)
        return deadline, tenant, None

    def _peer_hint(self, request: web.Request) -> str | None:
        """The validated x-kv-owner-hint, with a hint naming THIS engine
        dropped: a failover re-pick can deliver a migrate-stamped request
        back to the owner itself, and probing oneself over HTTP from the
        step thread (which holds the engine lock the handler needs) would
        stall an admission for the full peer timeout."""
        hint = peer_hint_from_headers(request.headers)
        if hint and hint == self._advertised_url():
            return None
        return hint

    # -- request tracing (docs/28-request-tracing.md) ----------------------

    def _trace_start(self, request: web.Request, rid: str, **attrs):
        """Open the engine-side timeline for one HTTP request, joining the
        router's trace via the inbound W3C traceparent header (a request
        without one starts a fresh engine-local trace)."""
        return self.traces.start(
            rid, "engine.request",
            traceparent=request.headers.get("traceparent"),
            attrs={"path": request.path, "model": self.model_name, **attrs},
        )

    def _trace_refused(self, trace, resp, rid: str):
        """Admission refusals (429 shed / 503 deadline / 503 draining) end
        the timeline immediately — short-circuits are exactly the requests
        a timeline must explain, and every refusal carries the correlation
        id the router's access log will echo."""
        trace.event("refused", status=resp.status)
        self.traces.finish(trace, status=f"refused:{resp.status}")
        resp.headers.setdefault("X-Request-Id", rid)
        return resp

    def _trace_respond(self, trace, resp, rid: str):
        """Terminal bookkeeping for a non-streaming response: stamp the
        correlation id and close the timeline with the HTTP outcome."""
        self.traces.finish(
            trace,
            status="ok" if resp.status < 400 else f"error:{resp.status}",
        )
        resp.headers.setdefault("X-Request-Id", rid)
        return resp

    def _trace_output(self, trace, out, choice: int = 0) -> None:
        """Record one resolved step's delta; on the terminal output, turn
        the request's lifecycle stamps into queue/prefill/decode phase
        spans and feed the tpu:request_* histograms. Rollback-safe: the
        engine only emits outputs for RESOLVED steps, so a discarded
        speculative dispatch can never appear here."""
        if out.new_token_ids:
            if out.num_output_tokens == len(out.new_token_ids):
                trace.event("first_token", choice=choice)
            sw = getattr(out, "spec_window", None)
            if sw is not None:
                # speculative-verify window (docs/36-speculative-decoding
                # .md): per-window acceptance rides the event, so a
                # timeline shows exactly where drafts paid off (or didn't)
                trace.event(
                    "decode_window", tokens=len(out.new_token_ids),
                    choice=choice, proposed=sw[0], accepted=sw[1],
                    proposer=sw[2],
                )
            else:
                trace.event(
                    "decode_window", tokens=len(out.new_token_ids),
                    choice=choice,
                )
        if not out.finished:
            return
        # structured output (docs/41-structured-output.md): the terminal
        # automaton verdict — "invalid" here with finish_reason=length is
        # the classic under-budgeted max_tokens truncation signature
        so = getattr(out, "structured_outcome", None)
        if so:
            trace.event("structured_outcome", outcome=so, choice=choice)
        # XLA compile stalls this request's dispatches blocked on
        # (docs/42-compile-telemetry.md): each names the program key and
        # wall — the timeline's explanation of a seconds-scale hole in an
        # otherwise steady decode cadence
        for st in getattr(out, "compile_stalls", None) or []:
            trace.event("compile_stall", choice=choice, **st)
        # getattr: error outputs (and RequestOutput-shaped test doubles)
        # carry no lifecycle to attribute
        pt = getattr(out, "phase_times", None)
        if not pt:
            return
        # hydration attribution (docs/30-kv-flow-telemetry.md): where the
        # prompt's KV came from — the timeline's explanation of a fast (or
        # slow) prefill, and the per-request view behind the
        # tpu:request_prefix_tokens_total counters. Emitted before the
        # phase spans so /debug/requests?rid= shows it with the prefill
        # span it explains.
        hyd = getattr(out, "hydration", None)
        if hyd:
            plan = getattr(out, "hydration_chunks", None)
            if plan:
                # compute-or-load planner (docs/31-hydration-planner.md):
                # the per-chunk decisions and outcomes that produced this
                # partition — which chunks adopted a tier fetch, which
                # fell back to recompute and why
                trace.event("kv_hydration", choice=choice, plan=plan, **hyd)
            else:
                trace.event("kv_hydration", choice=choice, **hyd)
        # ONE monotonic→epoch anchor for the whole timeline: converting
        # each stamp independently (mono_to_epoch per call) drifts the
        # shared phase boundaries apart by float noise
        anchor = mono_to_epoch(0.0)
        finish_e = anchor + pt["finish"]
        arrival_e = anchor + pt["arrival"]
        seat = pt.get("first_seat")
        ftok = pt.get("first_token")
        trace.span(
            "engine.queue", start=arrival_e,
            end=anchor + seat if seat is not None else finish_e,
            choice=choice,
        )
        if seat is not None:
            trace.span(
                "engine.prefill", start=anchor + seat,
                end=anchor + ftok if ftok is not None else finish_e,
                choice=choice,
                prompt_tokens=pt["prompt_tokens"],
                cached_prompt_tokens=pt["cached_prompt_tokens"],
            )
        if ftok is not None:
            trace.span(
                "engine.decode", start=anchor + ftok, end=finish_e,
                choice=choice, output_tokens=pt["output_tokens"],
                finish_reason=out.finish_reason or "",
                preemptions=pt["preemptions"],
            )
        # the contract histograms observe REGARDLESS of the tracing flag —
        # latency metrics are not a debug feature
        self.metrics.observe_request(pt, trace.trace_id or None)

    async def _resolve_grammar(self, body, trace=None):
        """(grammar, error_response) for a request's structured-output
        surface (docs/41-structured-output.md). A forced tool choice
        ("required" / a named function) wins over response_format — the
        forced call IS the response shape. Compilation runs in the
        executor (a pathological schema costs real milliseconds) and hits
        the engine's GrammarCache; behavior on an uncompilable schema
        follows EngineConfig.structured_output:

          enforce  -> 400 here (counted outcome=invalid),
          fallback -> decode unconstrained (counted outcome=fallback),
          off      -> constraints always declined (counted fallback).

        Malformed request SURFACES (response_format of an unknown type,
        tool_choice naming an absent function) are 400 in every mode —
        they are client errors, not grammar blowups."""
        from .grammar import (
            GrammarCompileError,
            extract_spec,
            tool_choice_spec,
        )

        try:
            spec = tool_choice_spec(
                getattr(body, "tools", None), getattr(body, "tool_choice", None)
            ) or extract_spec(body.response_format, body.guided_json)
        except GrammarCompileError as e:
            self.engine.count_structured("invalid")
            return None, error(400, f"structured output: {e}")
        if spec is None:
            return None, None
        mode = self.engine.config.structured_output
        if mode == "off":
            self.engine.count_structured("fallback")
            if trace is not None:
                trace.event("grammar", mode=mode, outcome="fallback")
            return None, None
        try:
            grammar, cached = await asyncio.get_running_loop().run_in_executor(
                None, self.engine.grammar_cache.get, spec
            )
        except GrammarCompileError as e:
            if mode == "enforce":
                self.engine.count_structured("invalid")
                return None, error(400, f"structured output: {e}")
            self.engine.count_structured("fallback")
            if trace is not None:
                trace.event(
                    "grammar", mode=mode, outcome="fallback", error=str(e)
                )
            return None, None
        if trace is not None:
            trace.event(
                "grammar", mode=mode, kind=spec.get("kind"), cached=cached,
                states=grammar.n_states, classes=grammar.n_classes,
                build_ms=round(grammar.build_s * 1000.0, 3),
            )
        return grammar, None

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = ChatCompletionRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError) as e:
            return error(400, f"invalid request: {e}")
        if not 1 <= body.n <= MAX_N_CHOICES:
            return error(400, f"n must be between 1 and {MAX_N_CHOICES}")
        if (err := self._check_model(body.model)) is not None:
            return err
        lora_name = body.model if body.model in self.lora_adapters else None
        messages = [m.model_dump() for m in body.messages]
        use_tools = bool(body.tools) and body.tool_choice != "none"
        if use_tools or any(
            m.get("role") == "tool" or m.get("tool_calls") for m in messages
        ):
            from .tool_calls import render_messages

            messages = render_messages(
                messages, body.tools if use_tools else None, body.tool_choice
            )
        prompt = self.async_engine.chat_prompt(messages)
        sampling = body.sampling(DEFAULT_MAX_TOKENS)
        if (err := self._check_logprobs(sampling)) is not None:
            return err
        rid = request.headers.get("X-Request-Id") or random_id("chatcmpl")
        trace = self._trace_start(
            request, rid, stream=bool(body.stream), n=body.n,
        )
        deadline, tenant, refused = self._gate_admission(request)
        if refused is not None:
            return self._trace_refused(trace, refused, rid)
        if tenant is not None:
            trace.set(tenant=tenant.tenant_id, priority=tenant.priority)
        trace.event("admitted")
        grammar, gerr = await self._resolve_grammar(body, trace)
        if gerr is not None:
            return self._trace_refused(trace, gerr, rid)
        if grammar is not None:
            import dataclasses

            sampling = dataclasses.replace(sampling, grammar=grammar)
        kv_hint = self._peer_hint(request)
        if body.stream:
            return await self._stream(
                request, rid, prompt, sampling, body, chat=True,
                lora_name=lora_name, parse_tools=use_tools, n=body.n,
                deadline=deadline, tenant=tenant, trace=trace,
                kv_owner_hint=kv_hint,
            )
        return await self._complete(
            rid, prompt, sampling, chat=True, lora_name=lora_name,
            parse_tools=use_tools, n=body.n, deadline=deadline,
            tenant=tenant, trace=trace, kv_owner_hint=kv_hint,
        )

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = CompletionRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError) as e:
            return error(400, f"invalid request: {e}")
        if not 1 <= body.n <= MAX_N_CHOICES:
            return error(400, f"n must be between 1 and {MAX_N_CHOICES}")
        if (err := self._check_model(body.model)) is not None:
            return err
        lora_name = body.model if body.model in self.lora_adapters else None
        prompt, prompt_ids = self._resolve_prompt(body.prompt)
        if prompt is None and prompt_ids is None:
            return error(400, "batched prompts are not supported yet")
        sampling = body.sampling(DEFAULT_MAX_TOKENS)
        if (err := self._check_logprobs(sampling)) is not None:
            return err
        # echo: the prompt text precedes the completion (vLLM/OpenAI
        # legacy semantics). Prompt LOGPROBS under echo would need a
        # scoring forward pass — refuse rather than silently omit
        echo_text = None
        if body.echo:
            if sampling.logprobs is not None:
                return error(
                    400, "echo with logprobs is not supported "
                    "(prompt logprobs are not computed)",
                )
            echo_text = (
                prompt if prompt is not None
                else await asyncio.get_running_loop().run_in_executor(
                    None, self.async_engine.detokenize, prompt_ids
                )
            )
        rid = request.headers.get("X-Request-Id") or random_id("cmpl")
        trace = self._trace_start(
            request, rid, stream=bool(body.stream), n=body.n,
        )
        deadline, tenant, refused = self._gate_admission(request)
        if refused is not None:
            return self._trace_refused(trace, refused, rid)
        if tenant is not None:
            trace.set(tenant=tenant.tenant_id, priority=tenant.priority)
        trace.event("admitted")
        grammar, gerr = await self._resolve_grammar(body, trace)
        if gerr is not None:
            return self._trace_refused(trace, gerr, rid)
        if grammar is not None:
            import dataclasses

            sampling = dataclasses.replace(sampling, grammar=grammar)
        kv_hint = self._peer_hint(request)
        if body.stream:
            return await self._stream(
                request, rid, prompt, sampling, body, chat=False,
                prompt_ids=prompt_ids, lora_name=lora_name, n=body.n,
                echo_text=echo_text, deadline=deadline, tenant=tenant,
                trace=trace, kv_owner_hint=kv_hint,
            )
        return await self._complete(
            rid, prompt, sampling, chat=False, prompt_ids=prompt_ids,
            lora_name=lora_name, n=body.n, echo_text=echo_text,
            deadline=deadline, tenant=tenant, trace=trace,
            kv_owner_hint=kv_hint,
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        """OpenAI embeddings: last-token pooled decoder hidden states."""
        try:
            body = EmbeddingRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError) as e:
            return error(400, f"invalid request: {e}")
        model = body.model
        if (err := self._check_model(model)) is not None:
            return err
        if model in self.lora_adapters:
            return error(
                400,
                "embeddings through a LoRA adapter are not supported; use "
                "the base model name",
            )
        if body.encoding_format != "float":
            return error(
                400,
                f"encoding_format {body.encoding_format!r} is not supported "
                "(only 'float')",
            )
        if body.dimensions is not None:
            return error(
                400,
                "the dimensions parameter is not supported; vectors have "
                "the model's hidden size",
            )
        raw = body.input
        if isinstance(raw, str):
            inputs = [raw]
        elif isinstance(raw, list) and raw and isinstance(raw[0], int):
            inputs = [raw]
        elif isinstance(raw, list) and raw:
            inputs = raw
        else:
            return error(400, "input must be a string, token array, or list")
        try:
            vectors, n_tokens = await self.async_engine.embed(inputs)
        except ValueError as e:
            return error(400, str(e))
        except RuntimeError as e:
            return error(503, str(e), "service_unavailable")
        return web.json_response({
            "object": "list",
            "model": model,
            "data": [
                {"object": "embedding", "index": i, "embedding": v}
                for i, v in enumerate(vectors)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    async def score(self, request: web.Request) -> web.Response:
        """vLLM /v1/score: similarity of text_1 x text_2 pairs via
        last-token-pooled embeddings (decoder-only models have no
        cross-encoder head; cosine of the L2-normalized embedding vectors
        is the vLLM embedding-model scoring path). The reference router
        proxies this route to its engines (main_router.py:50-246)."""
        try:
            body = ScoreRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError) as e:
            return error(400, f"invalid request: {e}")
        if (err := self._check_model(body.model)) is not None:
            return err
        t1 = [body.text_1] if isinstance(body.text_1, str) else body.text_1
        t2 = [body.text_2] if isinstance(body.text_2, str) else body.text_2
        if not t1 or not t2:
            return error(400, "text_1 and text_2 must be non-empty")
        if len(t1) == 1:
            pairs = [(t1[0], d) for d in t2]
        elif len(t1) == len(t2):
            pairs = list(zip(t1, t2))
        else:
            return error(
                400,
                f"text_1 ({len(t1)}) and text_2 ({len(t2)}) must be the "
                "same length, or text_1 a single string",
            )
        try:
            scores, n_tokens = await self._pair_scores(pairs)
        except ValueError as e:
            return error(400, str(e))
        except RuntimeError as e:
            return error(503, str(e), "service_unavailable")
        return web.json_response({
            "id": random_id("score"),
            "object": "list",
            "model": body.model,
            "data": [
                {"object": "score", "index": i, "score": s}
                for i, s in enumerate(scores)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    async def rerank(self, request: web.Request) -> web.Response:
        """Jina/Cohere-style /v1/rerank served by vLLM engines: order
        `documents` by embedding similarity to `query`."""
        try:
            body = RerankRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError) as e:
            return error(400, f"invalid request: {e}")
        if (err := self._check_model(body.model)) is not None:
            return err
        if not body.documents:
            return error(400, "documents must be non-empty")
        if body.top_n is not None and body.top_n < 1:
            return error(400, "top_n must be >= 1")
        try:
            scores, n_tokens = await self._pair_scores(
                [(body.query, d) for d in body.documents]
            )
        except ValueError as e:
            return error(400, str(e))
        except RuntimeError as e:
            return error(503, str(e), "service_unavailable")
        order = sorted(
            range(len(scores)), key=lambda i: scores[i], reverse=True
        )
        if body.top_n is not None:
            order = order[: max(0, body.top_n)]
        results = []
        for i in order:
            entry = {"index": i, "relevance_score": scores[i]}
            if body.return_documents:
                entry["document"] = {"text": body.documents[i]}
            results.append(entry)
        return web.json_response({
            "id": random_id("rerank"),
            "model": body.model,
            "results": results,
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    async def _pair_scores(
        self, pairs: list[tuple[str, str]]
    ) -> tuple[list[float], int]:
        """Cosine similarity per (a, b) pair. Each distinct text embeds
        once (reranks share one query across every document)."""
        texts: list[str] = []
        index: dict[str, int] = {}
        for a, b in pairs:
            for t in (a, b):
                if t not in index:
                    index[t] = len(texts)
                    texts.append(t)
        vectors, n_tokens = await self.async_engine.embed(texts)
        arr = np.asarray(vectors, dtype=np.float32)
        scores = [
            float(np.dot(arr[index[a]], arr[index[b]])) for a, b in pairs
        ]
        return scores, n_tokens

    def _check_model(self, model: str):
        """vLLM-compatible 404 for unknown model/adapter names — the
        router's model-filtered dispatch and the LoRA controller's
        reconciliation both rely on names being authoritative.

        Callers MUST test the return `is not None`, never by truthiness: an
        unprepared aiohttp Response is a MutableMapping with no items, so
        `bool(error(...))` is False and a bare `if err := ...` silently
        skips the rejection (the bug behind test_unknown_model_404)."""
        if model != self.model_name and model not in self.lora_adapters:
            return error(
                404, f"model '{model}' not found", "not_found_error"
            )
        return None

    @staticmethod
    def _resolve_prompt(prompt) -> tuple[str | None, list[int] | None]:
        if isinstance(prompt, str):
            return prompt, None
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return None, prompt
        if (
            isinstance(prompt, list)
            and len(prompt) == 1
            and isinstance(prompt[0], str)
        ):
            return prompt[0], None
        return None, None

    def _check_logprobs(self, sampling):
        from .model_runner import LOGPROBS_TOPN

        if sampling.logprobs is not None and not (
            0 <= sampling.logprobs <= LOGPROBS_TOPN
        ):
            return error(
                400,
                f"logprobs/top_logprobs must be between 0 and {LOGPROBS_TOPN}",
            )
        if sampling.min_tokens < 0:
            return error(400, "min_tokens must be >= 0")
        if sampling.min_tokens > sampling.max_tokens:
            return error(
                400,
                f"min_tokens ({sampling.min_tokens}) cannot exceed "
                f"max_tokens ({sampling.max_tokens})",
            )
        # linear-scanned per accepted token on the step thread — bound it
        if len(sampling.stop_token_ids) > 64:
            return error(400, "stop_token_ids supports at most 64 ids")
        return None

    def _tok_entry(self, tid: int) -> tuple[str, list[int]]:
        """(display string, byte list) for one token, cached per id. Tokens
        with no representation get a unique placeholder — the legacy
        completions top_logprobs dict is keyed by the string, and collisions
        would silently drop alternatives."""
        tid = int(tid)
        cached = self._tok_repr_cache.get(tid)
        if cached is not None:
            return cached
        s, bts = self.engine.tokenizer.token_repr(tid)
        if not s:
            s = f"<token_{tid}>"
        entry = (s, list(bts if bts else s.encode("utf-8")))
        if len(self._tok_repr_cache) < 65536:
            self._tok_repr_cache[tid] = entry
        return entry

    def _chat_logprobs(self, toks, entries, n):
        """OpenAI chat logprobs.content entries for one delta."""
        content = []
        for tid, (chosen, top_ids, top_lps) in zip(toks, entries):
            s, bts = self._tok_entry(tid)
            top = []
            for i, l in zip(top_ids[:n], top_lps[:n]):
                ts, tb = self._tok_entry(i)
                top.append({"token": ts, "logprob": l, "bytes": tb})
            content.append({
                "token": s,
                "logprob": chosen,
                "bytes": bts,
                "top_logprobs": top,
            })
        return {"content": content}

    def _completion_logprobs(self, toks, entries, n, offset0=0):
        """Legacy completions logprobs block for one delta."""
        tokens, token_logprobs, top_logprobs, text_offset = [], [], [], []
        off = offset0
        for tid, (chosen, top_ids, top_lps) in zip(toks, entries):
            s, _ = self._tok_entry(tid)
            tokens.append(s)
            token_logprobs.append(chosen)
            top_logprobs.append(
                {
                    self._tok_entry(i)[0]: l
                    for i, l in zip(top_ids[:n], top_lps[:n])
                }
            )
            text_offset.append(off)
            off += len(s)
        return {
            "tokens": tokens,
            "token_logprobs": token_logprobs,
            "top_logprobs": top_logprobs,
            "text_offset": text_offset,
        }, off

    @staticmethod
    def _choice_rids(rid: str, n: int) -> list[str]:
        """Per-choice engine request ids — ONE derivation shared by the
        stream and non-stream paths (cleanup/log correlation key on it)."""
        return [rid if i == 0 else f"{rid}-{i}" for i in range(n)]

    async def _tokenize_once_for_fanout(self, prompt, prompt_ids, n):
        """n>1 submits the same prompt n times — encode it ONCE here and
        hand every choice the ids (tokenization of a multi-KB rendered
        chat prompt is the expensive part of _submit)."""
        if n > 1 and prompt is not None and prompt_ids is None:
            loop = asyncio.get_running_loop()
            prompt_ids = await loop.run_in_executor(
                None, self.async_engine.tokenize, prompt
            )
            prompt = None
        return prompt, prompt_ids

    @staticmethod
    def _nth_sampling(sampling, i: int):
        """Per-choice sampling for n>1: an explicit seed derives seed+i
        (deterministic-but-distinct choices, vLLM's convention); without a
        seed the engine's RNG stream already decorrelates requests."""
        if i == 0 or sampling.seed is None:
            return sampling
        import dataclasses

        return dataclasses.replace(sampling, seed=sampling.seed + i)

    async def _run_single(self, rid, prompt, sampling, prompt_ids, lora_name,
                          deadline=None, parent_rid=None, tenant=None,
                          trace=None, choice=0, kv_owner_hint=None):
        """One full generation; returns the accumulated result dict.
        parent_rid (the HTTP request's base id) exempts sibling choices of
        the same n>1 request from this submission's admission count — a
        request gates against OTHER requests, never against itself."""
        text = ""
        token_ids: list[int] = []
        lp_entries: list = []
        finish_reason = None
        n_prompt = 0
        async for out in self.async_engine.generate(
            prompt=prompt, prompt_token_ids=prompt_ids,
            sampling=sampling, request_id=rid, lora_name=lora_name,
            deadline=deadline, admission_exclude_prefix=parent_rid,
            tenant=tenant, kv_owner_hint=kv_owner_hint,
        ):
            text += out.text_delta
            token_ids.extend(out.new_token_ids)
            if out.new_logprobs:
                lp_entries.extend(out.new_logprobs)
            finish_reason = out.finish_reason
            n_prompt = out.num_prompt_tokens
            if trace is not None:
                self._trace_output(trace, out, choice)
        return {
            "text": text, "token_ids": token_ids, "lp": lp_entries,
            "finish_reason": finish_reason, "n_prompt": n_prompt,
        }

    async def _complete(
        self, rid, prompt, sampling, *, chat: bool, prompt_ids=None,
        lora_name=None, parse_tools: bool = False, n: int = 1,
        echo_text: str | None = None, deadline: float | None = None,
        tenant=None, trace=None, kv_owner_hint=None,
    ) -> web.Response:
        if trace is None:
            trace = self.traces.start(rid, "engine.request")
        # n>1: concurrent submissions — continuous batching runs them in
        # one batch and the prefix cache dedups the shared prompt, so the
        # marginal cost per extra choice is its decode tokens only.
        # Tasks (not bare gather): the first failure CANCELS the siblings
        # — cancellation triggers generate()'s abort, freeing their KV
        # blocks instead of decoding to max_tokens for a doomed response
        prompt, prompt_ids = await self._tokenize_once_for_fanout(
            prompt, prompt_ids, n
        )
        tasks = [
            asyncio.ensure_future(self._run_single(
                crid, prompt,
                self._nth_sampling(sampling, i), prompt_ids, lora_name,
                deadline, parent_rid=rid, tenant=tenant,
                trace=trace, choice=i, kv_owner_hint=kv_owner_hint,
            ))
            for i, crid in enumerate(self._choice_rids(rid, n))
        ]
        try:
            runs = await asyncio.gather(*tasks)
        except (ValueError, EngineSleepingError, RuntimeError) as e:
            for t in tasks:
                if not t.done():
                    t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if (resp := self._admission_error(e)) is not None:
                # raced past the handler's gate: same mapping
                return self._trace_respond(trace, resp, rid)
            if isinstance(e, ValueError):
                return self._trace_respond(trace, error(400, str(e)), rid)
            if isinstance(e, EngineSleepingError):
                return self._trace_respond(
                    trace, error(503, str(e), "service_unavailable"), rid
                )
            return self._trace_respond(
                trace, error(500, str(e), "internal_error"), rid
            )
        for r in runs:
            if r["finish_reason"] == "error":
                return self._trace_respond(
                    trace, error(500, r["text"], "internal_error"), rid
                )
            if r["finish_reason"] == "shed" and not r["token_ids"]:
                # evicted from the waiting queue by a higher-priority
                # admission before producing anything: same HTTP shape as
                # admission-time shedding (429 + Retry-After), so clients
                # handle both the same way
                import math

                waiting, queued = self.engine.queue_depth()
                retry = self.engine.estimate_retry_after_s(queued)
                return self._trace_refused(
                    trace,
                    error(
                        429,
                        "request shed for a higher-priority admission; "
                        "retry",
                        "overloaded",
                        headers={"Retry-After": str(int(math.ceil(retry)))},
                    ),
                    rid,
                )
        created = int(time.time())
        choices = []
        for i, r in enumerate(runs):
            finish_reason = r["finish_reason"]
            if chat:
                message = {"role": "assistant", "content": r["text"]}
                if parse_tools:
                    from .tool_calls import parse_tool_calls

                    content, calls = parse_tool_calls(r["text"])
                    if calls:
                        message = {"role": "assistant", "content": content,
                                   "tool_calls": calls}
                        finish_reason = "tool_calls"
                choice = {
                    "index": i,
                    "message": message,
                    "finish_reason": finish_reason,
                }
                if sampling.logprobs is not None:
                    choice["logprobs"] = self._chat_logprobs(
                        r["token_ids"], r["lp"], sampling.logprobs
                    )
            else:
                choice = {"index": i,
                          "text": (echo_text or "") + r["text"],
                          "finish_reason": finish_reason}
                if sampling.logprobs is not None:
                    choice["logprobs"], _ = self._completion_logprobs(
                        r["token_ids"], r["lp"], sampling.logprobs
                    )
            choices.append(choice)
        return self._trace_respond(
            trace,
            web.json_response(
                {
                    "id": rid,
                    "object": "chat.completion" if chat else "text_completion",
                    "created": created,
                    "model": self.model_name,
                    "system_fingerprint": self.system_fingerprint,
                    "choices": choices,
                    # prompt counted once; completion tokens sum over choices
                    "usage": usage(
                        runs[0]["n_prompt"],
                        sum(len(r["token_ids"]) for r in runs),
                    ),
                }
            ),
            rid,
        )

    async def _stream(
        self, request, rid, prompt, sampling, body, *, chat: bool,
        prompt_ids=None, lora_name=None, parse_tools: bool = False,
        n: int = 1, echo_text: str | None = None,
        deadline: float | None = None, tenant=None, trace=None,
        kv_owner_hint=None,
    ) -> web.StreamResponse:
        """SSE streaming for 1..n choices — ONE implementation (n=1 is a
        single pump), so single- and parallel-sampling semantics can never
        diverge. Each choice is its own engine request; chunks interleave
        on the wire tagged with their choice index (the OpenAI n>1 stream
        contract). Tool-call splitting and logprobs run per choice; every
        token-bearing step emits a chunk, even when detok held the text
        back — first-token latency is only observable if the first token's
        chunk actually goes out."""
        if trace is None:
            trace = self.traces.start(rid, "engine.request")
        if self.async_engine.is_sleeping:
            return self._trace_respond(
                trace, error(503, "engine is sleeping", "service_unavailable"),
                rid,
            )
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": rid,
            },
        )
        await resp.prepare(request)
        created = int(time.time())
        obj = "chat.completion.chunk" if chat else "text_completion"
        include_usage = bool(
            body.stream_options and body.stream_options.include_usage
        )
        prompt, prompt_ids = await self._tokenize_once_for_fanout(
            prompt, prompt_ids, n
        )
        rids = self._choice_rids(rid, n)
        queue: asyncio.Queue = asyncio.Queue()

        async def pump(i: int) -> None:
            try:
                async for out in self.async_engine.generate(
                    prompt=prompt, prompt_token_ids=prompt_ids,
                    sampling=self._nth_sampling(sampling, i),
                    request_id=rids[i], lora_name=lora_name,
                    deadline=deadline, admission_exclude_prefix=rid,
                    tenant=tenant, kv_owner_hint=kv_owner_hint,
                ):
                    await queue.put((i, out))
            except Exception as e:
                # invalid prompt (too long) or raced into sleep/death
                # after the SSE headers went out: delivered as an error
                # event by the consumer, then DONE
                await queue.put((i, e))
            await queue.put((i, None))

        tasks = [asyncio.ensure_future(pump(i)) for i in range(n)]
        from .tool_calls import ToolCallStreamParser

        parsers = [
            ToolCallStreamParser() if parse_tools and chat else None
            for _ in range(n)
        ]
        n_prompt = 0
        n_out_total = 0
        lp_offs = [0] * n  # per-choice text offsets (completions logprobs)
        live = n
        sent_errors: set[str] = set()  # a request-wide failure (same
        # exception from every pump) emits ONE error event, not n copies

        async def send(payload: dict) -> None:
            await resp.write(f"data: {json.dumps(payload)}\n\n".encode())

        try:
            if chat:  # role preamble chunk per choice
                for i in range(n):
                    await send(self._chunk(
                        rid, obj, created, {"role": "assistant"}, None,
                        index=i,
                    ))
            elif echo_text:
                # echo: the prompt leads each choice's stream (vLLM
                # streams the same way — one prompt chunk, then deltas)
                for i in range(n):
                    await send(self._chunk(
                        rid, obj, created, echo_text, None, index=i,
                    ))
            while live:
                i, out = await queue.get()
                if out is None:
                    live -= 1
                    continue
                if isinstance(out, Exception):
                    if str(out) not in sent_errors:
                        sent_errors.add(str(out))
                        await send({"error": {"message": str(out)}})
                    continue
                n_prompt = out.num_prompt_tokens
                n_out_total += len(out.new_token_ids)
                self._trace_output(trace, out, choice=i)
                if out.finish_reason == "error":
                    # same dedup as pump exceptions: a step-thread death
                    # stamps the identical message into every choice
                    if out.text_delta not in sent_errors:
                        sent_errors.add(out.text_delta)
                        await send({"error": {"message": out.text_delta}})
                    continue
                if not (out.new_token_ids or out.text_delta or out.finished):
                    continue
                text_delta = out.text_delta
                finish = out.finish_reason if out.finished else None
                extra_delta = None
                if parsers[i] is not None:
                    text_delta = parsers[i].feed(text_delta)
                    if out.finished:
                        tail, calls = parsers[i].finish()
                        text_delta += tail
                        if calls:
                            extra_delta = {
                                "content": text_delta or None,
                                "tool_calls": [
                                    {**c, "index": ci}
                                    for ci, c in enumerate(calls)
                                ],
                            }
                            finish = "tool_calls"
                delta = (
                    extra_delta
                    if extra_delta is not None
                    else ({"content": text_delta} if chat else text_delta)
                )
                chunk = self._chunk(rid, obj, created, delta, finish, index=i)
                if sampling.logprobs is not None and out.new_logprobs:
                    if chat:
                        chunk["choices"][0]["logprobs"] = self._chat_logprobs(
                            out.new_token_ids, out.new_logprobs,
                            sampling.logprobs,
                        )
                    else:
                        chunk["choices"][0]["logprobs"], lp_offs[i] = (
                            self._completion_logprobs(
                                out.new_token_ids, out.new_logprobs,
                                sampling.logprobs, lp_offs[i],
                            )
                        )
                await send(chunk)
        except ConnectionResetError:
            # no abort-by-name here: _submit renames colliding request ids,
            # so abort(rids[i]) could kill a DIFFERENT live request that
            # owns that name. The finally-cancel below reaches generate()'s
            # own cleanup, which aborts under the TRUE engine-side id.
            self.traces.finish(trace, status="disconnected")
            return resp
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            self.traces.finish(
                trace, status="error:stream" if sent_errors else "ok"
            )
        if include_usage:
            final = self._chunk(rid, obj, created, None, None)
            final["choices"] = []
            final["usage"] = usage(n_prompt, n_out_total)
            await send(final)
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    def _chunk(self, rid, obj, created, delta, finish_reason,
               index: int = 0) -> dict:
        if obj == "chat.completion.chunk":
            choice = {
                "index": index,
                "delta": delta if delta is not None else {},
                "finish_reason": finish_reason,
            }
        else:
            choice = {
                "index": index,
                "text": delta if isinstance(delta, str) else "",
                "finish_reason": finish_reason,
            }
        return {
            "id": rid,
            "object": obj,
            "created": created,
            "model": self.model_name,
            "system_fingerprint": self.system_fingerprint,
            "choices": [choice],
        }

    # -- discovery / control routes ---------------------------------------

    async def list_models(self, request: web.Request) -> web.Response:
        if self.draining:
            # discovery probes /v1/models: a 503 here is how the router
            # stops picking a draining engine within one probe interval
            return error(
                503, "engine is draining", "service_unavailable",
                headers={"X-Engine-Draining": "1"},
            )
        cards = [ModelCard(id=self.model_name)]
        cards += [
            ModelCard(id=name, parent=self.model_name, root=path)
            for name, path in self.lora_adapters.items()
        ]
        return web.json_response(ModelList(data=cards).model_dump())

    @property
    def draining(self) -> bool:
        return not self.async_engine.accepting

    def _begin_drain(self, exit_after: bool = False) -> None:
        """Idempotent drain trigger (POST /drain and SIGTERM both land
        here). A later exit_after=True (SIGTERM after a preStop /drain)
        still exits once the running drain's barrier passes."""
        self.async_engine.begin_drain()
        loop = asyncio.get_running_loop()
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = loop.create_task(self._do_drain(exit_after))
        elif exit_after:
            async def _exit_when_drained():
                await self._drained.wait()
                raise web.GracefulExit()

            # strong ref: the loop holds tasks only weakly, and a GC'd
            # exit task would leave a SIGTERM'd pod running forever
            if self._exit_task is None or self._exit_task.done():
                self._exit_task = loop.create_task(_exit_when_drained())

    async def _do_drain(self, exit_after: bool) -> None:
        """Finish in-flight streams (bounded), flush the KV event log,
        deregister from the KV controller — then optionally exit the
        process (SIGTERM path) inside the grace period."""
        t0 = time.monotonic()
        idle = await self.async_engine.wait_idle(self.drain_timeout_s)
        if not idle:
            logger.warning(
                "drain timeout (%.1fs) with requests still in flight; "
                "proceeding", self.drain_timeout_s,
            )
        if self.kv_event_publisher is not None:
            try:
                await self.kv_event_publisher.flush()
            except Exception as e:  # flush is best-effort on the way out
                logger.warning("KV event flush during drain failed: %s", e)
            await self.kv_event_publisher.stop()
            self.kv_event_publisher = None
            self.engine.threads.unregister("kv_event_publisher")
        await self._register_with_kv_controller("/deregister")
        self._drained.set()
        logger.info(
            "drain complete in %.2fs (idle=%s)", time.monotonic() - t0, idle
        )
        if exit_after:
            # GracefulExit unwinds web.run_app through its normal cleanup
            raise web.GracefulExit()

    async def drain(self, request: web.Request) -> web.Response:
        """POST /drain: stop admissions, finish in-flight work, flush +
        deregister. ?wait=true blocks until the drain barrier passes (the
        helm preStop hook uses this so SIGTERM only ever lands on a drained
        process). The process does NOT exit — that's SIGTERM's job."""
        already = self.draining
        self._begin_drain(exit_after=False)
        if request.query.get("wait", "").lower() in ("1", "true", "yes"):
            await self._drained.wait()
        return web.json_response(
            {
                "status": "draining",
                "already_draining": already,
                "drained": self._drained.is_set(),
                "drain_timeout_s": self.drain_timeout_s,
            },
            status=200 if self._drained.is_set() else 202,
        )

    async def set_role(self, request: web.Request) -> web.Response:
        """POST /role {"role": "prefill"|"decode"}: flip the engine's
        disaggregated pool role and RE-ADMIT it (docs/40-pool-rebalancing
        .md). The rebalancer drains the engine first (POST /drain?wait=
        true), so arriving here mid-drain means waiting out the barrier;
        arriving with no drain at all is also legal (the flip phase
        re-POSTs idempotently). Refused 409 on the SIGTERM exit path —
        the process is going down, not changing jobs."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        role = body.get("role")
        from .. import metrics_contract as mc

        if role not in mc.POOL_ROLE_VALUES:
            return error(
                400,
                f"role must be one of {list(mc.POOL_ROLE_VALUES)}",
                "invalid_request_error",
            )
        if self._exit_task is not None and not self._exit_task.done():
            return error(
                409, "engine is exiting (SIGTERM drain in progress)",
                "engine_exiting",
            )
        async with self._role_lock:
            was_draining = self.draining
            if self._drain_task is not None and not self._drain_task.done():
                # let the in-flight drain finish its barrier + deregister
                # before resurrecting the engine under the new identity
                try:
                    await asyncio.wait_for(
                        self._drained.wait(),
                        timeout=self.drain_timeout_s + 10.0,
                    )
                except asyncio.TimeoutError:
                    return error(
                        409, "drain barrier did not pass in time",
                        "engine_draining",
                    )
            previous = self.pool_role
            self.pool_role = role
            self.metrics.set_pool_role(role)
            # reopen admissions and reset the drain latch so a LATER
            # drain/SIGTERM starts a fresh barrier
            self.async_engine.end_drain()
            self._drain_task = None
            self._drained = asyncio.Event()
            if self.kv_event_publisher is None:
                self._start_kv_event_publisher()
            # re-register under the new role; the controller re-adds the
            # engine to its set and the router's next scrape follows the
            # advertised tpu:pool_role
            await self._register_with_kv_controller("/register")
        logger.info("pool role flip: %s -> %s (was_draining=%s)",
                    previous, role, was_draining)
        return web.json_response({
            "status": "ok",
            "role": role,
            "previous_role": previous,
            "was_draining": was_draining,
        })

    def _overload_state(self) -> str | None:
        """Reason the engine would currently shed a plain request, or None.
        Drives /ready so readiness flips BEFORE collapse. record=False:
        kubelet probe polls must not inflate tpu:requests_shed_total."""
        try:
            self.async_engine.precheck_admission(record=False)
        except EngineDrainingError:
            return "draining"
        except EngineOverloadedError as e:
            return str(e)
        return None

    async def health(self, request: web.Request) -> web.Response:
        """Liveness: 503 only when the step loop is dead (a draining or
        overloaded engine is still alive — restarting it would kill the
        in-flight streams drain exists to protect). Queue/drain state rides
        in the body; /ready is the readiness view."""
        if not self.async_engine.is_healthy:
            return web.json_response({"status": "dead"}, status=503)
        waiting, queued_tokens = self.engine.queue_depth()
        return web.json_response({
            "status": "draining" if self.draining else "ok",
            "draining": self.draining,
            "role": self.pool_role,
            "waiting_requests": waiting,
            "queued_tokens": queued_tokens,
            "overloaded": self._overload_state(),
        })

    async def ready(self, request: web.Request) -> web.Response:
        """Readiness: 503 while dead, draining, shedding, or STALLED (the
        watchdog's verdict — a wedged engine leaves the Service so traffic
        fails over, while /health liveness stays green: restarting it
        would destroy the very evidence /debug/flight and the postmortem
        exist to capture)."""
        if not self.async_engine.is_healthy:
            return web.json_response({"status": "dead"}, status=503)
        if self.watchdog is not None and self.watchdog.stalled is not None:
            return web.json_response(
                {
                    "status": "not_ready",
                    "reason": "stalled",
                    "stall": self.watchdog.stalled,
                },
                status=503,
            )
        reason = self._overload_state()
        if reason is not None:
            return web.json_response(
                {"status": "not_ready", "reason": reason}, status=503
            )
        return web.json_response({"status": "ok"})

    async def metrics_endpoint(self, request: web.Request) -> web.Response:
        om = wants_openmetrics(request)
        # fleet-coherence series owned by the server, not the engine
        # snapshot: publisher health + stickiness-audit counts
        pub = self.kv_event_publisher
        try:
            events_log = self.engine.scheduler.pool.events
        except AttributeError:  # engine test doubles carry no pool
            events_log = None
        self.metrics.update_fleet_health(
            publish_batches=pub.posts if pub is not None else 0,
            publish_failures=pub.publish_failures if pub is not None else 0,
            # depth is meaningful only with a publisher draining the log:
            # a standalone engine (no KV_CONTROLLER_URL) fills the bounded
            # buffer and parks at capacity — exporting that would be a
            # permanent false "publisher can't keep up" alarm
            pending_depth=(
                events_log.pending_depth()
                if pub is not None and events_log is not None else 0
            ),
            subscribers=len(pub.subscribers) if pub is not None else 0,
            stickiness=self.stickiness.counts(),
        )
        # thread-liveness series (docs/37-flight-recorder.md): ages are
        # computed HERE from the registry's beat stamps — a dead watchdog
        # cannot freeze its own gauge
        self.metrics.update_liveness(
            ages=self.engine.threads.ages(),
            stall_counts=(
                self.watchdog.stall_counts
                if self.watchdog is not None else None
            ),
        )
        payload = self.metrics.render(
            await self.async_engine.stats_async(), openmetrics=om
        )
        if om:
            # full content-type (incl. version params) — aiohttp's
            # content_type= kwarg rejects parameters
            return web.Response(
                body=payload,
                headers={"Content-Type": OPENMETRICS_CONTENT_TYPE},
            )
        return web.Response(body=payload, content_type="text/plain")

    # one-liner per mounted debug endpoint — the GET /debug index (they
    # number six+ now and were discoverable only by reading this file)
    DEBUG_ENDPOINTS = {
        "GET /debug": "this index",
        "GET /debug/timing": "step-thread wall-time decomposition, "
                             "submit-lock waits, program-cache state",
        "GET /debug/hydration": "compute-or-load planner live inputs + "
                                "decision counters (docs/31)",
        "GET /debug/requests": "tracing-spine timelines; ?rid= one full "
                               "trace (docs/28)",
        "GET /debug/flight": "flight-recorder ring + heartbeat table + "
                             "watchdog state (docs/37)",
        "GET /debug/programs": "XLA program inventory: compile walls, "
                               "dispatch counts, storm state (docs/42)",
        "POST /debug/postmortem": "write (or return) a redacted postmortem "
                                  "JSON black box now (docs/37)",
        "POST /debug/profile/start": "start an xprof device capture "
                                     "({\"dir\": ...})",
        "POST /debug/profile/stop": "stop the capture and flush the dump",
    }

    async def debug_index(self, request: web.Request) -> web.Response:
        """GET /debug: every mounted debug endpoint with a one-liner."""
        return web.json_response({"endpoints": self.DEBUG_ENDPOINTS})

    async def debug_requests(self, request: web.Request) -> web.Response:
        """Tracing spine introspection (docs/28-request-tracing.md):
        recent / slowest / in-flight request timelines; ?rid= returns one
        full trace (every span + event) as JSON."""
        payload, status = self.traces.debug_response(request.query)
        return web.json_response(payload, status=status)

    def _postmortem_context(self) -> dict:
        """Extra postmortem sections (flightrec.PostmortemDumper calls
        this at dump time, possibly from a dying thread or a signal
        handler — everything here is lock-light reads)."""
        eng = self.engine
        ctx: dict = {
            "config": {
                "model": self.model_name,
                "fingerprint": eng.model_fingerprint,
                "async_scheduling": eng.config.async_scheduling,
                "kv_hydration": eng.config.kv_hydration,
            },
            "timing": dict(eng.timing),
            "loop_timing": dict(self.async_engine.loop_timing),
        }
        if self.watchdog is not None:
            ctx["watchdog"] = self.watchdog.snapshot()
        try:
            snap = eng.flow.snapshot()
            ctx["hydration"] = {
                "signal": eng.hydration_signal(),
                "decisions": snap.get("decisions", {}),
                "sources": snap.get("hydration", {}),
            }
        except Exception as e:  # a half-built engine still gets a dump
            ctx["hydration"] = {"error": f"{type(e).__name__}: {e}"}
        return ctx

    async def debug_flight(self, request: web.Request) -> web.Response:
        """GET /debug/flight: the live black box — last flight records
        (?last= bounds them), the heartbeat table, and the watchdog's
        state/counters (docs/37-flight-recorder.md)."""
        try:
            last = int(request.query.get("last", "128"))
        except ValueError:
            return error(400, "last must be an integer")
        eng = self.engine
        body = {
            "recording": eng.flightrec.enabled,
            "records_total": eng.flightrec.records_total,
            "flight": eng.flightrec.snapshot(last=max(1, last)),
            "heartbeats": eng.threads.snapshot(),
            "watchdog": (
                self.watchdog.snapshot()
                if self.watchdog is not None else None
            ),
            "postmortems": {
                "dir": self.postmortems.out_dir or None,
                "written": self.postmortems.dumps_written,
                "last_path": self.postmortems.last_path,
            },
        }
        out = eng.flightrec.outstanding_age_s()
        if out is not None:
            body["outstanding_step"] = {
                "age_s": round(out[0], 3), "kind": out[1],
            }
        return web.json_response(body)

    async def debug_programs(self, request: web.Request) -> web.Response:
        """GET /debug/programs: the CompileWatch program inventory —
        every recorded build's key, compile wall, dispatch count,
        last-used age and HBM footprint, plus cache hit/miss totals and
        the storm detector's state (docs/42-compile-telemetry.md). The
        storm runbook starts here: find the mid_traffic entry, read its
        key, fix the bucket ladder that let the shape through."""
        return web.json_response(self.engine.compile_watch.debug_payload())

    async def debug_postmortem(self, request: web.Request) -> web.Response:
        """POST /debug/postmortem: dump the black box NOW. With
        --postmortem-dir configured the file is written (path in the
        reply); without it the full redacted document comes back inline —
        the operator's escape hatch on an ephemeral filesystem. The dump
        walks every thread stack, so it runs off the event loop."""
        path, doc = await asyncio.get_running_loop().run_in_executor(
            None, self.postmortems.dump, "manual", "POST /debug/postmortem"
        )
        if path is not None:
            return web.json_response({"status": "written", "path": path})
        return web.json_response({"status": "inline", "postmortem": doc})

    async def debug_profile_start(self, request: web.Request) -> web.Response:
        """On-demand xprof capture on a live engine: wraps
        jax.profiler.start_trace so a slow phase seen in /debug/requests
        or /debug/timing can be drilled into at the device level without
        restarting the pod. Load the dump in XProf/TensorBoard."""
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except json.JSONDecodeError:
                return error(400, "body must be JSON (or empty)")
        log_dir = (body or {}).get("dir") or "/tmp/tpu-xprof"
        if self._profile_dir is not None:
            return error(
                409, f"already profiling to {self._profile_dir}", "conflict"
            )
        try:
            import jax

            jax.profiler.start_trace(log_dir)
        except ImportError:
            return error(501, "jax is not available in this process")
        except Exception as e:  # another tracer already active, bad dir...
            return error(409, f"profiler refused to start: {e}", "conflict")
        self._profile_dir = log_dir
        logger.info("xprof capture started -> %s", log_dir)
        return web.json_response({"status": "profiling", "dir": log_dir})

    async def debug_profile_stop(self, request: web.Request) -> web.Response:
        if self._profile_dir is None:
            return error(409, "no profile capture in progress", "conflict")
        log_dir, self._profile_dir = self._profile_dir, None
        try:
            import jax

            # stop_trace flushes the dump to disk — do it off the loop
            await asyncio.get_running_loop().run_in_executor(
                None, jax.profiler.stop_trace
            )
        except Exception as e:
            return error(500, f"profiler stop failed: {e}", "internal_error")
        logger.info("xprof capture stopped (%s)", log_dir)
        return web.json_response({"status": "stopped", "dir": log_dir})

    async def debug_timing(self, request: web.Request) -> web.Response:
        """Served-stack profiling: where the step thread's wall time goes
        (device dispatch vs host scheduling vs idle) and how long request
        submissions wait on the engine lock. Counters are cumulative and
        monotonic — profilers snapshot before/after and subtract (an
        in-place reset would race the step thread's unlocked accumulates
        and could be silently lost)."""
        eng = self.async_engine.engine
        sched = eng.scheduler
        spec: dict = {
            "proposed": dict(sched.spec_proposed_by),
            "accepted": dict(sched.spec_accepted_by),
        }
        if sched.draft_proposer is not None:
            # draft-proposer pool discipline (docs/36): rows that fell
            # back to n-gram under pool pressure, and the scratch share
            # the draft currently holds out of the shared block pool
            spec["draft"] = {
                "declined_rows": sched.draft_proposer.declined_rows,
                "scratch_blocks": sched.pool.scratch_blocks,
            }
        return web.json_response({
            "engine": dict(eng.timing),
            "loop": dict(self.async_engine.loop_timing),
            "spec": spec,
            "programs": {
                "compile_fallbacks": eng.runner.compile_fallbacks,
                "bg_compiles": eng.runner.bg_compiles,
                "compiled_keys": len(eng.runner._compiled_keys),
                "bg_pending": len(eng.runner._bg_inflight),
            },
        })

    async def debug_hydration(self, request: web.Request) -> web.Response:
        """Operator view of the compute-or-load hydration planner
        (docs/31-hydration-planner.md): the LIVE decision inputs —
        per-tier measured fetch bandwidth + sample-floor state, achieved
        prefill FLOP/s, per-block KV bytes — alongside the cumulative
        per-chunk decision counters and the planner's configuration.
        Exactly the numbers the planner acted on, not a reconstruction."""
        eng = self.engine

        def work():
            sig = eng.hydration_signal()
            snap = eng.flow.snapshot()
            hydr = getattr(eng, "hydrator", None)
            return {
                "signal": sig,
                "decisions": snap.get("decisions", {}),
                "hydration_sources": snap.get("hydration", {}),
                "planner": (
                    hydr.snapshot() if hydr is not None
                    else {"mode": eng.config.kv_hydration, "enabled": False}
                ),
            }

        data = await asyncio.get_running_loop().run_in_executor(None, work)
        return web.json_response(data)

    async def sleep(self, request: web.Request) -> web.Response:
        level = int(request.query.get("level", "1"))
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self.async_engine.sleep, level
            )
        except RuntimeError as e:
            return error(409, str(e), "conflict")
        return web.json_response({"status": "sleeping", "level": level})

    async def wake_up(self, request: web.Request) -> web.Response:
        await asyncio.get_running_loop().run_in_executor(
            None, self.async_engine.wake
        )
        return web.json_response({"status": "awake"})

    async def is_sleeping(self, request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": self.async_engine.is_sleeping})

    async def load_lora_adapter(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        path = body.get("lora_path")
        if not name or not path:
            return error(400, "lora_name and lora_path are required")
        try:
            await self.async_engine.load_lora(name, path)
        except (ValueError, KeyError, FileNotFoundError) as e:
            return error(400, str(e))
        except RuntimeError as e:
            return error(409, str(e), "conflict")
        logger.info("loaded LoRA adapter %s from %s", name, path)
        return web.json_response({"status": "ok"})

    async def unload_lora_adapter(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        try:
            await self.async_engine.unload_lora(name)
        except KeyError:
            return error(404, f"adapter {name} not loaded", "not_found_error")
        except RuntimeError as e:  # in-flight requests still use the adapter
            return error(409, str(e), "conflict")
        return web.json_response({"status": "ok"})

    async def kv_lookup(self, request: web.Request) -> web.Response:
        """KV-aware routing probe: longest resident KV prefix for a prompt
        (HBM + host tiers). The KV controller fans /lookup out to this."""
        body = await request.json()
        text, token_ids = body.get("text"), body.get("token_ids")
        if text is None and token_ids is None:
            return error(400, "text or token_ids is required")
        n = await self.async_engine.kv_lookup(
            text=text, token_ids=token_ids, lora_name=body.get("model")
        )
        return web.json_response({"matched_tokens": n})

    def _device_identity(self) -> dict | None:
        """This engine's advertised mesh identity, or None: transport
        opt-in (kv_peer_transport auto|device) AND a live 2+-process
        jax.distributed runtime with KV_MESH_GROUP assigned. An engine
        can be a device-pull OWNER without consuming the peer tier, so
        this doesn't require peer_tier."""
        cfg = getattr(self.engine, "config", None)
        if cfg is None or getattr(
            cfg, "kv_peer_transport", "http"
        ) not in ("auto", "device"):
            return None
        peer = getattr(self.engine, "peer_tier", None)
        if peer is not None and peer.transport_identity is not None:
            return peer.transport_identity
        from .kv_device_transfer import device_transport_identity

        return device_transport_identity()

    @staticmethod
    def _parse_peer_hashes(body: dict) -> list[int] | None:
        """Decimal-string hash list of one peer probe/fetch, bounded and
        validated; None = malformed (caller 400s)."""
        raw = body.get("hashes")
        if not isinstance(raw, list):
            return None
        try:
            return [int(h) for h in raw[:MAX_PEER_RUN_BLOCKS]]
        except (TypeError, ValueError):
            return None

    async def kv_peer_contains(self, request: web.Request) -> web.Response:
        """Peer-engine KV tier, probe half (docs/35-peer-kv-reuse.md):
        how many of the requested hashes this engine can serve RIGHT NOW,
        consecutively, from its local tiers — the staleness guard a
        peer's hydration planner runs before planning chunks against the
        cluster index's possibly-seconds-old view of this pool."""
        body = await request.json()
        if body.get("fingerprint") != self.engine.model_fingerprint:
            return error(
                409, "KV fingerprint mismatch — refusing foreign probe",
                "conflict",
            )
        hashes = self._parse_peer_hashes(body)
        if hashes is None:
            return error(400, "hashes must be a list of decimal strings")
        n = await self.async_engine.kv_peer_contains(hashes)
        reply: dict = {"matched": n}
        identity = self._device_identity()
        if identity is not None:
            # echo this owner's mesh identity so the probing peer can
            # (re-)negotiate the transport against a FRESH view — the
            # owner-hint path never consults the controller, and a stale
            # index hint must re-validate here before any collective
            reply["transport"] = identity
        return web.json_response(reply)

    async def kv_peer_fetch(self, request: web.Request) -> web.Response:
        """Peer-engine KV tier, sender half: the consecutive locally-
        resident prefix of the requested hashes as kvstore-framed block
        payloads (engine/kv_transfer raw_frame — the same wire the remote
        store and the PD stream speak). The engine lock is held only for
        the residency walk + device-copy dispatch; numpy resolution, disk
        reads and framing run in an executor, and the served bytes meter
        under (tier="peer", direction="out"). With an at-rest codec the
        peer link ships WIRE form (int4+scales / fp8): ring entries held
        encoded frame as-is, logical arrays encode here — the puller
        dequantizes at its pool's adopt boundary."""
        from .kv_codec import logical_nbytes, wire_nbytes
        from .kv_transfer import encoded_frame

        body = await request.json()
        if body.get("fingerprint") != self.engine.model_fingerprint:
            return error(
                409, "KV fingerprint mismatch — refusing foreign fetch",
                "conflict",
            )
        hashes = self._parse_peer_hashes(body)
        if hashes is None:
            return error(400, "hashes must be a list of decimal strings")
        t0 = time.perf_counter()
        served, entries = await self.async_engine.kv_peer_export(hashes)

        def build() -> tuple[bytes, int, int, int]:
            host = self.engine.host_tier
            disk = getattr(host, "disk", None) if host is not None else None
            codec = self.engine.kv_codec
            frames: list[bytes] = []
            nbytes = 0
            logical = 0
            for h, (kind, val) in zip(served, entries):
                if kind == "dev":
                    obj = np.stack([np.asarray(p) for p in val])
                elif kind == "np":
                    obj = val  # ndarray, or EncodedKVBlock (encode_ring)
                else:  # "disk": file IO deferred off the engine lock
                    obj = disk.load(val) if disk is not None else None
                    if obj is None:
                        break  # evicted since the walk: stop clean
                if codec.enabled and isinstance(obj, np.ndarray):
                    obj = codec.encode(obj)
                frames.append(encoded_frame(h, obj))
                nbytes += wire_nbytes(obj)
                logical += logical_nbytes(obj)
            return b"".join(frames), len(frames), nbytes, logical

        payload, count, nbytes, logical = await asyncio.get_running_loop(
        ).run_in_executor(None, build)
        # peer/out: WIRE bytes this engine SERVED to a peer (failure paths
        # on the puller's side record their own 0-byte samples)
        self.engine.flow.record(
            "peer", "out", nbytes, count, time.perf_counter() - t0,
            logical_nbytes=logical,
        )
        return web.Response(
            body=payload,
            content_type="application/octet-stream",
            headers={
                "X-KV-Count": str(count),
                "X-KV-Fingerprint": self.engine.model_fingerprint,
            },
        )

    async def kv_peer_device_pull(self, request: web.Request) -> web.Response:
        """Owner trigger of a device-collective peer pull (docs/39): the
        puller POSTs the hash run, then BOTH processes meet inside the
        same cooperative transfer program (kv_device_transfer.pull_kv_
        device_crossproc). The handler always enters the collective once
        the run parses — the program's own fingerprint allgather and
        go/no-go barrier abort both sides cooperatively, so a refusal
        can never leave the puller wedged mid-collective. The reply
        lands only after the owner's half completes (the puller reads
        it AFTER its own half — split send/read)."""
        body = await request.json()
        hashes = self._parse_peer_hashes(body)
        if hashes is None:
            return error(400, "hashes must be a list of decimal strings")
        try:
            await self.async_engine.kv_peer_device_serve(hashes)
        except Exception as e:
            # aborted cooperatively (fingerprint gate, peer prep failure,
            # unsupported mesh shape) — the puller already degraded its
            # chunk to fallback_recompute; this status is informational
            logger.warning("device peer pull serve aborted: %s", e)
            return error(409, f"device pull aborted: {e}", "conflict")
        return web.json_response({"ok": True})

    async def kv_peer_replicate(self, request: web.Request) -> web.Response:
        """Proactive flash-crowd replication, target half (docs/39): the
        controller orders THIS engine to fetch a hot prefix from its
        owner (HTTP peer path) and adopt it parked — after which the
        cluster index shows a second holder and the router can fan the
        crowd out. The wire fetch runs off the step lock."""
        body = await request.json()
        owner = str(body.get("owner") or "").rstrip("/")
        hashes = self._parse_peer_hashes(body)
        if not owner or not owner.startswith("http") or hashes is None:
            return error(400, "need owner url and a hash list")
        n = await self.async_engine.kv_peer_replicate(owner, hashes)
        return web.json_response({"adopted": n})

    async def kv_replicated(self, request: web.Request) -> web.Response:
        """Replication notification to the OWNER: a peer now holds copies
        of these hashes, so migration-aware eviction prefers them as
        victims from here on (pool + host ring, docs/39)."""
        body = await request.json()
        hashes = self._parse_peer_hashes(body)
        if hashes is None:
            return error(400, "hashes must be a list of decimal strings")
        n = await self.async_engine.kv_mark_replicated(hashes)
        return web.json_response({"resident": n})

    async def kv_export(self, request: web.Request) -> web.Response:
        """Disaggregated prefill, sender side: the prompt's resident KV
        blocks as an npz payload (engine/kv_transfer.py wire format)."""
        from .kv_transfer import serialize_blocks

        body = await request.json()
        if body.get("text") is None and body.get("token_ids") is None:
            return error(400, "text or token_ids is required")
        hashes, blocks = await self.async_engine.kv_export(
            text=body.get("text"), token_ids=body.get("token_ids"),
            lora_name=body.get("model"),
        )
        # multi-MB payloads: never serialize on the event loop
        payload = await asyncio.get_running_loop().run_in_executor(
            None, serialize_blocks, hashes, blocks,
            self.engine.model_fingerprint,
        )
        return web.Response(
            body=payload,
            content_type="application/octet-stream",
            headers={"X-KV-Blocks": str(len(hashes))},
        )

    async def kv_export_stream(self, request: web.Request) -> web.StreamResponse:
        """Streaming sender: the prompt's resident KV blocks as
        self-delimiting frames (kv_transfer.block_frame). The engine lock is
        held only to walk the chain and dispatch the device→host copies;
        each block resolves to numpy and hits the socket while later
        copies are still in flight — no whole-prompt staging buffer
        (VERDICT r2 weak #3)."""
        import numpy as np

        from .kv_transfer import block_frame

        body = await request.json()
        if body.get("text") is None and body.get("token_ids") is None:
            return error(400, "text or token_ids is required")
        hashes, parts = await self.async_engine.kv_export_lazy(
            text=body.get("text"), token_ids=body.get("token_ids"),
            lora_name=body.get("model"),
        )
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "application/octet-stream"
        resp.headers["X-KV-Blocks"] = str(len(hashes))
        resp.headers["X-KV-Fingerprint"] = self.engine.model_fingerprint
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        for h, p in zip(hashes, parts):
            frame = await loop.run_in_executor(
                None,
                lambda h=h, p=p: block_frame(
                    h, np.stack([np.asarray(x) for x in p])
                ),
            )
            await resp.write(frame)
        await resp.write_eof()
        return resp

    async def kv_import(self, request: web.Request) -> web.Response:
        """Disaggregated prefill, receiver side: adopt shipped KV blocks."""
        from .kv_transfer import deserialize_blocks

        payload = await request.read()
        try:
            hashes, blocks, fp = await asyncio.get_running_loop(
            ).run_in_executor(None, deserialize_blocks, payload)
        except Exception as e:
            return error(400, f"malformed KV payload: {e}")
        try:
            n = await self.async_engine.kv_import(hashes, blocks, fp)
        except ValueError as e:  # geometry or fingerprint mismatch
            return error(409, str(e), "conflict")
        return web.json_response({"imported_blocks": n, "offered": len(hashes)})

    async def kv_pull(self, request: web.Request) -> web.Response:
        """Disaggregated prefill orchestration target: fetch the prompt's KV
        from the prefill engine (source_url) and adopt it locally. The router
        calls this on the DECODE engine between its two phases
        (reference request.py:305-431; NIXL receiver role)."""
        import aiohttp

        from .kv_transfer import deserialize_blocks

        body = await request.json()
        source = (body.get("source_url") or "").rstrip("/")
        if not source:
            return error(400, "source_url is required")
        if body.get("messages") is not None:
            probe = {"text": self.async_engine.chat_prompt(body["messages"])}
        elif body.get("text") is not None:
            probe = {"text": body["text"]}
        elif body.get("token_ids") is not None:
            probe = {"token_ids": body["token_ids"]}
        else:
            return error(400, "messages, text, or token_ids is required")
        if body.get("model"):
            probe["model"] = body["model"]
        try:
            return await self._pull_streamed(source, probe)
        except _StreamUnsupported:
            pass  # older sender: fall back to the one-shot npz hop
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return error(502, f"source engine unreachable: {e}", "bad_gateway")
        except ValueError as e:
            return error(409, str(e), "conflict")
        try:
            async with self._client_session().post(
                source + "/kv/export", json=probe
            ) as resp:
                if resp.status != 200:
                    return error(
                        502, f"source engine returned {resp.status}",
                        "bad_gateway",
                    )
                payload = await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return error(502, f"source engine unreachable: {e}", "bad_gateway")
        try:
            hashes, blocks, fp = await asyncio.get_running_loop(
            ).run_in_executor(None, deserialize_blocks, payload)
        except Exception as e:  # truncated/corrupt upstream payload
            return error(502, f"malformed KV payload from source: {e}",
                         "bad_gateway")
        try:
            n = await self.async_engine.kv_import(hashes, blocks, fp)
        except ValueError as e:
            return error(409, str(e), "conflict")
        return web.json_response({"imported_blocks": n, "offered": len(hashes)})

    # adopt in groups of this many blocks: each group's device upload runs
    # under a BRIEF engine lock while the next group downloads, so transfer
    # pipelines with decode instead of stalling it for the whole import
    _PULL_GROUP = 8

    async def _pull_streamed(self, source: str, probe: dict) -> web.Response:
        """Receiver half of the streaming PD path: read frames off the
        sender's chunked response and adopt them group-by-group."""
        import aiohttp
        import numpy as np

        from .kv_transfer import FrameParser

        async with self._client_session().post(
            source + "/kv/export_stream", json=probe
        ) as resp:
            if resp.status == 404:
                raise _StreamUnsupported
            if resp.status != 200:
                raise aiohttp.ClientError(
                    f"source engine returned {resp.status}"
                )
            fp = resp.headers.get("X-KV-Fingerprint", "")
            offered = int(resp.headers.get("X-KV-Blocks", "0"))
            if offered and fp != self.engine.model_fingerprint:
                # refuse before moving any bytes onto the device
                raise ValueError(
                    f"KV fingerprint mismatch: sender {fp!r} != this "
                    f"engine {self.engine.model_fingerprint!r} — refusing "
                    "foreign KV"
                )
            # bound each frame at a small multiple of this engine's own
            # per-block byte size — a corrupted stream fails fast instead of
            # buffering the rest of the response as residual bytes
            from .kv_transfer import engine_block_nbytes

            block_nbytes = (
                engine_block_nbytes(self.engine.runner)
                if self.engine.runner.kv_caches else 64 << 20
            )
            parser = FrameParser(max_frame_bytes=max(4 * block_nbytes, 1 << 20))
            batch_h: list[int] = []
            batch_b: list[np.ndarray] = []
            imported = 0

            async def adopt_batch():
                nonlocal imported
                if not batch_h:
                    return
                imported += await self.async_engine.kv_import(
                    list(batch_h), np.stack(batch_b), fp
                )
                batch_h.clear()
                batch_b.clear()

            async for chunk in resp.content.iter_any():
                try:
                    frames = parser.feed(chunk)
                except Exception as e:
                    # corrupt stream bytes are a bad-gateway condition (like
                    # a malformed npz payload → 502), NOT a 409 conflict —
                    # kv_pull's ValueError clause is for fingerprint/geometry
                    # mismatches. Broad on purpose: garbled headers surface
                    # as KeyError/TypeError/AttributeError too (missing
                    # nbytes, unknown dtype string — same family
                    # kv_disk_tier.load handles)
                    raise aiohttp.ClientPayloadError(
                        f"corrupt KV stream from {source}: "
                        f"{type(e).__name__}: {e}"
                    ) from e
                for h, arr in frames:
                    batch_h.append(h)
                    batch_b.append(arr)
                    if len(batch_h) >= self._PULL_GROUP:
                        await adopt_batch()
            await adopt_batch()
            if parser.residual:
                logger.warning(
                    "KV stream from %s ended mid-frame (%d residual bytes); "
                    "adopted %d complete blocks", source, parser.residual,
                    imported,
                )
        return web.json_response(
            {"imported_blocks": imported, "offered": offered,
             "transport": "stream"}
        )

    async def tokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        ids = self.async_engine.tokenize(body.get("prompt", ""))
        return web.json_response({"tokens": ids, "count": len(ids)})

    async def detokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        text = self.async_engine.detokenize(body.get("tokens", []))
        return web.json_response({"prompt": text})

    async def version(self, request: web.Request) -> web.Response:
        return web.json_response({"version": __version__})


# -- CLI -------------------------------------------------------------------


def _parse_bool_flag(v: str) -> bool:
    """Strict true/false parser — a typo like 'off' must not silently mean
    True (the flag often gates a correctness bisection)."""
    s = str(v).lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {v!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU LLM serving engine")
    p.add_argument("--model", default="tiny-llama",
                   help="preset name or local HF checkpoint dir")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-model-len", type=int, default=None)
    p.add_argument("--dtype", default=None, choices=[None, "bfloat16", "float32"])
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=512,
                   help="HBM KV pages in the pool")
    p.add_argument("--num-host-blocks", type=int, default=0,
                   help="host-RAM KV offload tier size in blocks (0 = off)")
    p.add_argument("--host-kv-gib", type=float, default=0.0,
                   help="host-RAM KV offload tier byte budget in GiB — the "
                        "operator-facing unit (LMCACHE_MAX_LOCAL_CPU_SIZE "
                        "equivalent); overrides --num-host-blocks when "
                        "larger")
    p.add_argument("--remote-kv-url", default="",
                   help="remote KV store URL (tpukv://host:port, "
                        "kvstore/server.py) — the LMCACHE_REMOTE_URL lm:// "
                        "equivalent; enables cross-engine KV sharing")
    p.add_argument("--disk-kv-dir", default="",
                   help="local-disk KV tier directory (ring evictions "
                        "persist here; LMCACHE_LOCAL_DISK equivalent)")
    p.add_argument("--disk-kv-gib", type=float, default=0.0,
                   help="disk KV tier byte budget in GiB (0 = off)")
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-num-batched-tokens", type=int, default=512)
    p.add_argument("--max-waiting-requests", type=int, default=0,
                   help="admission control: bound on the waiting queue — "
                        "beyond it new requests get 429 + Retry-After "
                        "computed from observed decode throughput "
                        "(0 = unbounded)")
    p.add_argument("--max-queued-tokens", type=int, default=0,
                   help="admission control: watermark on queued prompt "
                        "tokens awaiting prefill; beyond it new requests "
                        "are shed with 429 (0 = unbounded)")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="graceful drain bound (SIGTERM / POST /drain): "
                        "in-flight streams get this long to finish before "
                        "the KV flush + deregister + exit proceed anyway — "
                        "keep below terminationGracePeriodSeconds")
    p.add_argument("--pool-role", default="",
                   choices=["", "prefill", "decode"],
                   help="disaggregated pool role this engine BOOTS with "
                        "(docs/40-pool-rebalancing.md). A runtime "
                        "property: POST /role flips it live and the "
                        "engine re-registers + advertises tpu:pool_role; "
                        "empty = not in a disaggregated deployment")
    p.add_argument("--request-tracing", default=True, type=_parse_bool_flag,
                   help="per-request span timelines (docs/28-request-"
                        "tracing.md): admission, queue wait, prefill, "
                        "per-decode-window events, joined to the router's "
                        "trace via the inbound traceparent header and "
                        "served by /debug/requests. 'false' keeps only "
                        "the tpu:request_* latency histograms")
    p.add_argument("--trace-buffer", type=int, default=256,
                   help="finished request timelines kept in the in-process "
                        "ring buffer behind /debug/requests")
    p.add_argument("--flight-recording", default=True, type=_parse_bool_flag,
                   help="flight recorder (docs/37-flight-recorder.md): "
                        "bounded ring of structured step records "
                        "(dispatch/resolve seq, batch shape, queue/pool "
                        "depths, rollback/fault markers) served by "
                        "/debug/flight and carried in stall reports and "
                        "postmortems. 'false' disables the ring; the "
                        "watchdog's unresolved-step liveness cursor stays "
                        "on either way")
    p.add_argument("--flight-records", type=int, default=512,
                   help="flight-recorder ring capacity (the last-N step "
                        "records a stall report / postmortem carries)")
    p.add_argument("--watchdog", default=True, type=_parse_bool_flag,
                   help="thread-liveness watchdog (docs/37): stale "
                        "heartbeats and dispatched-but-never-resolved "
                        "steps become a named stall — one structured "
                        "report, tpu:engine_step_stalls_total, a "
                        "postmortem dump, and /ready flips 503 (liveness "
                        "/health never flips) until the stall clears")
    p.add_argument("--watchdog-interval-s", type=float, default=1.0,
                   help="seconds between watchdog liveness checks")
    p.add_argument("--watchdog-stall-s", type=float, default=120.0,
                   help="step-thread / unresolved-dispatch stall threshold "
                        "in seconds (keep above the longest legitimate "
                        "lazy-compile stall; per-loop thresholds for the "
                        "fetcher/publisher/bg-compile ride their own "
                        "registrations)")
    p.add_argument("--compile-watch", default=True, type=_parse_bool_flag,
                   help="XLA compile telemetry (docs/42-compile-"
                        "telemetry.md): record every program build "
                        "(inventory at /debug/programs, "
                        "tpu:engine_compiles_total{phase,trigger} / "
                        "compile-seconds histogram / program-cache "
                        "hit-miss counters, compile_stall trace events) "
                        "plus the recompile-storm detector. 'false' "
                        "disables the watch entirely")
    p.add_argument("--compile-storm-threshold", type=int, default=6,
                   help="mid-traffic compiles (sync compiles on the "
                        "dispatch path after warmup — shapes the bucket "
                        "ladder failed to absorb) inside the sliding "
                        "window that trip a recompile storm: one "
                        "structured report naming the offending shapes + "
                        "tpu:engine_compile_storms_total (backs the "
                        "TpuRecompileStorm alert)")
    p.add_argument("--compile-storm-window-s", type=float, default=300.0,
                   help="recompile-storm sliding window in seconds")
    p.add_argument("--postmortem-dir", default="",
                   help="directory for redacted postmortem JSON dumps "
                        "(watchdog trip, SIGQUIT, fatal step-thread "
                        "death, POST /debug/postmortem). Empty = no files "
                        "(/debug/postmortem then returns the document "
                        "inline)")
    p.add_argument("--step-metering", default=True, type=_parse_bool_flag,
                   help="per-step saturation accounting (docs/29-"
                        "saturation-slo.md): decode-seat occupancy, "
                        "padding-waste fraction, achieved-FLOP/s → MFU and "
                        "the tpu:engine_step_* histograms, metered in the "
                        "step loop. 'false' disables the meter; the "
                        "goodput token ledger (tpu:goodput_tokens_total / "
                        "tpu:wasted_tokens_total) stays on either way")
    p.add_argument("--kv-flow-metering", default=True, type=_parse_bool_flag,
                   help="per-tier KV transfer metering (docs/30-kv-flow-"
                        "telemetry.md): bytes/blocks/latency per tier move "
                        "(tpu:kv_transfer_*) and the per-tier bandwidth "
                        "estimators (tpu:kv_tier_bandwidth_bytes_per_s) "
                        "behind the compute-or-load hydration signal. "
                        "'false' disables the transfer meters; the "
                        "hydration attribution counters "
                        "(tpu:request_prefix_tokens_total) AND the "
                        "bandwidth estimators (the hydration planner's "
                        "decision input) stay on either way")
    p.add_argument("--kv-hydration", default="auto",
                   choices=["auto", "planner", "sync", "off"],
                   help="compute-or-load KV hydration for disk/remote-"
                        "resident prefixes (docs/31-hydration-planner.md): "
                        "auto chunks the resident run and picks "
                        "load-vs-recompute per chunk from measured tier "
                        "bandwidth vs prefill FLOP/s, pipelining async "
                        "fetches with chunked prefill (sync-load fallback "
                        "below the bandwidth sample floor); planner always "
                        "plans (unmeasured tiers recompute); sync is the "
                        "legacy blocking whole-prefix reload; off ignores "
                        "lower-tier residency (recompute-only)")
    p.add_argument("--kv-hydration-chunk-blocks", type=int, default=16,
                   help="hydration planner chunk granularity in KV blocks "
                        "(the fetch/adopt/decide unit)")
    p.add_argument("--kv-hydration-timeout-s", type=float, default=0.0,
                   help="seconds a planned chunk fetch may run before the "
                        "chunk falls back to recompute; 0 = auto (3x the "
                        "plan's own fetch estimate, clamped to [0.5, 30])")
    p.add_argument("--kv-peer-fetch", default=False, type=_parse_bool_flag,
                   help="peer-engine KV tier (docs/35-peer-kv-reuse.md): "
                        "let the hydration planner pull a prefix resident "
                        "only in ANOTHER engine's HBM/host tiers "
                        "(tier=peer, priced per chunk against recompute/"
                        "disk/remote from measured bandwidth). Owner "
                        "discovery: the router's x-kv-owner-hint stamp, "
                        "else a cluster-index lookup against the first "
                        "KV_CONTROLLER_URL subscriber. The serving "
                        "endpoints (/kv/peer_contains, /kv/peer_fetch) "
                        "are always mounted regardless")
    p.add_argument("--kv-peer-transport", default="http",
                   choices=["http", "device", "auto"],
                   help="wire of the peer KV tier (docs/39-device-peer-kv"
                        ".md): http always pulls over /kv/peer_fetch; "
                        "auto/device advertise this engine's mesh identity "
                        "(KV_MESH_GROUP + jax.distributed shape) through "
                        "KV registration and pull over ICI/DCN device "
                        "collectives when the owner shares the mesh, "
                        "falling back to HTTP otherwise; device "
                        "additionally warns when no identity is available")
    p.add_argument("--kv-peer-fetch-timeout-s", type=float, default=2.0,
                   help="per-round-trip timeout of peer lookups/probes/"
                        "fetches (probes run on the step thread, so this "
                        "bounds an admission's worst-case stall on a slow "
                        "peer); the hydration plan deadline "
                        "(--kv-hydration-timeout-s) still governs when a "
                        "pending peer chunk falls back to recompute")
    p.add_argument("--prefill-buckets", default="",
                   help="comma-separated prefill chunk buckets (default: "
                        "pow2 ladder up to --max-num-batched-tokens). "
                        "FEWER buckets = fewer XLA programs = faster "
                        "warmup and fewer lazy-compile stalls, at the cost "
                        "of padding small chunks up")
    p.add_argument("--decode-buckets", default="",
                   help="comma-separated decode batch buckets (default: "
                        "pow2 ladder up to --max-num-seqs)")
    p.add_argument("--width-floor-blocks", type=int, default=64,
                   help="floor of the context-width program ladder in pool "
                        "blocks — lower = tighter KV gathers but more "
                        "compiled programs (see SchedulerConfig)")
    p.add_argument("--decode-window", type=int, default=8,
                   help="decode iterations fused into one device dispatch; "
                        "raise on high-RTT links (remote chips) — dispatch "
                        "overhead amortizes over window x batch tokens, at "
                        "the cost of up to window-1 discarded tokens past a "
                        "stop condition")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--pipeline-parallel-size", type=int, default=1,
                   help="GSPMD stage sharding of the layer axis (multi-host)")
    p.add_argument("--data-parallel-size", type=int, default=1)
    p.add_argument("--sequence-parallel-size", type=int, default=1,
                   help="ring-attention context parallelism: shard prefill "
                        "chunks' sequence axis over an sp ring "
                        "(parallel/ring_attention.py); size it for "
                        "long-context / prefill-role engines")
    p.add_argument("--expert-parallel-size", type=int, default=1,
                   help="MoE expert parallelism: shard Mixtral-family "
                        "expert FFNs over an ep mesh axis")
    p.add_argument("--num-speculative-tokens", type=int, default=0,
                   help="speculative decoding: propose up to this many "
                        "tokens and verify them in one dispatch (greedy "
                        "requests only; 0 disables). Composes with the "
                        "pipelined step loop (docs/36)")
    p.add_argument("--speculative-min-ngram", type=int, default=2)
    p.add_argument("--speculative-config", default="ngram",
                   choices=["ngram", "draft"],
                   help="proposer: 'ngram' (prompt lookup, zero extra "
                        "weights) or 'draft' (a small draft model drafts "
                        "the k tokens, sharing the paged KV pool through a "
                        "scratch block namespace; n-gram stays the "
                        "fallback). Requires --num-speculative-tokens > 0")
    p.add_argument("--draft-model", default="",
                   help="registry name / checkpoint dir of the draft model "
                        "(--speculative-config draft); must share the "
                        "target model's tokenizer/vocabulary")
    p.add_argument("--structured-output", default="enforce",
                   choices=["enforce", "fallback", "off"],
                   help="grammar-constrained decoding (docs/41-structured-"
                        "output.md) for response_format / guided_json / "
                        "forced tool_choice: enforce compiles the schema "
                        "to an on-device token automaton (uncompilable "
                        "schemas get 400); fallback decodes such requests "
                        "unconstrained instead (counted "
                        "tpu:structured_requests_total{outcome=fallback}); "
                        "off declines all constraints")
    p.add_argument("--quantization", default=None,
                   choices=[None, "int8"],
                   help="weight-only quantization: int8 stores every linear "
                        "weight as int8 + per-output-channel scales (half "
                        "the weight HBM — how an 8B-class model fits one "
                        "16 GiB v5e chip)")
    p.add_argument("--attention-backend", default="auto",
                   choices=["auto", "xla", "pallas", "pallas_interpret"],
                   help="decode attention: auto picks the measured winner "
                        "for the pool's block size (the Pallas paged-decode "
                        "kernel at >=32-token pages with long context, XLA "
                        "staged attention otherwise)")
    p.add_argument("--prefill-attention-backend", default="auto",
                   choices=["auto", "xla", "pallas", "pallas_interpret"],
                   help="prefill/chunked-prefill attention, independent of "
                        "decode: pallas streams pool pages through the "
                        "paged flash-prefill kernel (no gather, no "
                        "(B,T,S) mask); auto gates on block size + context")
    p.add_argument("--kv-cache-dtype", default="auto",
                   choices=["auto", "fp8"],
                   help="KV pool storage dtype: fp8 (float8_e4m3fn) halves "
                        "KV HBM traffic and doubles pool capacity")
    p.add_argument("--kv-at-rest-codec", default="none",
                   choices=["none", "fp8", "int4"],
                   help="at-rest KV codec for blocks leaving the pool "
                        "(disk/remote/peer tiers): int4+per-group-scales "
                        "(~3.5x wire reduction) or fp8 passthrough; "
                        "dequantized on adopt. Joins the KV fingerprint "
                        "so mixed-precision fleets never cross-serve "
                        "(docs/38-kv-quantization.md)")
    p.add_argument("--kv-at-rest-group-size", type=int, default=32,
                   help="int4 codec quantization group size (elements per "
                        "shared scale); smaller = tighter error bound, "
                        "more scale overhead")
    p.add_argument("--kv-at-rest-host-ring", default=False,
                   type=_parse_bool_flag,
                   help="hold host-ring entries in at-rest wire form too: "
                        "the same host-RAM budget buys wire-ratio x more "
                        "blocks, at a dequant on every ring reload")
    p.add_argument("--async-scheduling", default=True,
                   type=_parse_bool_flag,
                   help="two-deep pipelined step loop: dispatch step N+1 "
                        "against speculatively-advanced state before step "
                        "N's tokens sync to the host (decode inputs chain "
                        "device-side; one D2H sync per resolved step). "
                        "Token streams are bitwise identical to the serial "
                        "loop. 'false' restores the serial "
                        "schedule→execute→sync→postprocess path")
    p.add_argument("--enable-prefix-caching", action="store_true", default=True)
    p.add_argument("--no-enable-prefix-caching", dest="enable_prefix_caching",
                   action="store_false")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--warmup", action="store_true",
                   help="compile the prefill/decode bucket programs before "
                        "accepting traffic (first requests otherwise stall "
                        "on 10-40s XLA compiles)")
    p.add_argument("--warmup-scope", default="full",
                   choices=["full", "coarse"],
                   help="full: the whole bucket ladder (deterministic "
                        "steady-state perf; tens of minutes cold, seconds "
                        "with a warm --compilation-cache-dir). coarse: only "
                        "the dominating shape lattice (minutes) — finer "
                        "programs pad up and compile in the background "
                        "with zero serving stalls")
    p.add_argument("--max-loras", type=int, default=0,
                   help="runtime LoRA adapter slots (0 disables LoRA)")
    p.add_argument("--max-lora-rank", type=int, default=8)
    p.add_argument("--distributed", default="auto",
                   choices=["auto", "on", "off"],
                   help="multi-host bootstrap via jax.distributed from the "
                        "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/"
                        "JAX_PROCESS_ID env the multi-host statefulset "
                        "exports; auto = initialize iff >1 process named")
    p.add_argument("--compilation-cache-dir",
                   default="/tmp/vllm-tpu-xla-cache",
                   help="persistent XLA compilation cache: --warmup costs "
                        "its 20-40s-per-program compiles ONCE per "
                        "(model, bucket-set); every later boot reloads "
                        "them in seconds. In k8s, mount a PVC here "
                        "(empty string disables)")
    return p


def engine_config_from_args(args: argparse.Namespace) -> EngineConfig:
    model_cfg = resolve_model_config(
        args.model, args.max_model_len, args.dtype,
        quantization=getattr(args, "quantization", None),
    )
    if getattr(args, "decode_buckets", ""):
        # sorted: bucket_for scans in tuple order for the first bucket >= n,
        # so an unordered list would silently pad everything to the first
        # (possibly oversized) entry
        decode_buckets = tuple(sorted(
            int(b) for b in args.decode_buckets.split(",") if b.strip()
        ))
    else:
        decode_buckets = tuple(
            b for b in (8, 16, 32, 64, 128, 256) if b <= args.max_num_seqs
        ) or (args.max_num_seqs,)
        if decode_buckets[-1] < args.max_num_seqs:
            decode_buckets += (args.max_num_seqs,)
    if getattr(args, "prefill_buckets", ""):
        prefill_buckets = tuple(sorted(
            int(b) for b in args.prefill_buckets.split(",") if b.strip()
        ))
    else:
        prefill_buckets = tuple(
            b for b in (64, 128, 256, 512, 1024, 2048)
            if b <= args.max_num_batched_tokens
        ) or (args.max_num_batched_tokens,)
        if prefill_buckets[-1] < args.max_num_batched_tokens:
            prefill_buckets += (args.max_num_batched_tokens,)
    return EngineConfig(
        model=model_cfg,
        cache=CacheConfig(
            block_size=args.block_size,
            kv_cache_dtype=args.kv_cache_dtype,
            num_blocks=args.num_blocks,
            num_host_blocks=args.num_host_blocks,
            host_kv_gib=args.host_kv_gib,
            disk_kv_dir=args.disk_kv_dir,
            disk_kv_gib=args.disk_kv_gib,
            remote_kv_url=args.remote_kv_url,
            enable_prefix_caching=args.enable_prefix_caching,
            kv_at_rest_codec=args.kv_at_rest_codec,
            kv_at_rest_group_size=args.kv_at_rest_group_size,
            kv_at_rest_host_ring=args.kv_at_rest_host_ring,
        ),
        scheduler=SchedulerConfig(
            max_num_seqs=args.max_num_seqs,
            max_num_batched_tokens=args.max_num_batched_tokens,
            decode_buckets=decode_buckets,
            prefill_buckets=prefill_buckets,
            decode_window=args.decode_window,
            width_floor_blocks=args.width_floor_blocks,
            num_speculative_tokens=args.num_speculative_tokens,
            speculative_min_ngram=args.speculative_min_ngram,
            speculative_method=getattr(args, "speculative_config", "ngram"),
            draft_model=getattr(args, "draft_model", ""),
            max_waiting_requests=getattr(args, "max_waiting_requests", 0),
            max_queued_tokens=getattr(args, "max_queued_tokens", 0),
        ),
        parallel=ParallelConfig(
            tensor_parallel_size=args.tensor_parallel_size,
            data_parallel_size=args.data_parallel_size,
            pipeline_parallel_size=args.pipeline_parallel_size,
            sequence_parallel_size=args.sequence_parallel_size,
            expert_parallel_size=args.expert_parallel_size,
        ),
        lora=LoRAConfig(
            max_loras=args.max_loras, max_lora_rank=args.max_lora_rank
        ),
        seed=args.seed,
        attention_backend=getattr(args, "attention_backend", "auto"),
        prefill_attention_backend=getattr(
            args, "prefill_attention_backend", "auto"
        ),
        async_scheduling=getattr(args, "async_scheduling", True),
        step_metering=getattr(args, "step_metering", True),
        kv_flow_metering=getattr(args, "kv_flow_metering", True),
        kv_hydration=getattr(args, "kv_hydration", "auto"),
        kv_hydration_chunk_blocks=getattr(
            args, "kv_hydration_chunk_blocks", 16
        ),
        kv_hydration_timeout_s=getattr(args, "kv_hydration_timeout_s", 0.0),
        kv_peer_fetch=getattr(args, "kv_peer_fetch", False),
        kv_peer_transport=getattr(args, "kv_peer_transport", "http"),
        kv_peer_fetch_timeout_s=getattr(
            args, "kv_peer_fetch_timeout_s", 2.0
        ),
        flight_recording=getattr(args, "flight_recording", True),
        flight_records=getattr(args, "flight_records", 512),
        structured_output=getattr(args, "structured_output", "enforce"),
        compile_watch=getattr(args, "compile_watch", True),
        compile_storm_threshold=getattr(
            args, "compile_storm_threshold", 6
        ),
        compile_storm_window_s=getattr(
            args, "compile_storm_window_s", 300.0
        ),
    )


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    # multi-host bootstrap BEFORE any JAX backend touch: the helm multi-host
    # statefulset exports JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/
    # JAX_PROCESS_ID per pod (parallel/distributed.py consumes them); after
    # this, jax.devices() spans every host in the slice and the engine's
    # mesh/pjit shardings cover them
    from ..parallel.distributed import maybe_initialize
    from ..utils.system import raise_fd_limit

    raise_fd_limit()
    maybe_initialize(args.distributed)
    if args.compilation_cache_dir:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", args.compilation_cache_dir
        )
        # the serving program set is all multi-second compiles; cache
        # everything that costs more than a second
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    config = engine_config_from_args(args)
    logger.info("starting engine for model=%s on %s:%d",
                args.model, args.host, args.port)
    engine = LLMEngine(config)
    if args.warmup:
        logger.info(
            "warming serving buckets (%s scope)...", args.warmup_scope
        )
        engine.warmup(scope=args.warmup_scope)
    server = EngineServer(
        engine,
        served_model_name=args.served_model_name,
        drain_timeout_s=args.drain_timeout_s,
        request_tracing=args.request_tracing,
        trace_buffer=args.trace_buffer,
        watchdog=args.watchdog,
        watchdog_interval_s=args.watchdog_interval_s,
        watchdog_stall_s=args.watchdog_stall_s,
        postmortem_dir=args.postmortem_dir,
        pool_role=args.pool_role,
    )
    web.run_app(server.build_app(), host=args.host, port=args.port,
                access_log=None)


if __name__ == "__main__":
    main()
