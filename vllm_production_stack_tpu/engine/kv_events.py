"""Engine-side KV event stream: the push half of the cluster KV index.

KV-aware routing used to be pull-based: every routed request made the KV
controller fan a /kv/lookup probe out to EVERY engine, each probe tokenizing
the prompt and walking the hash chain server-side — O(slowest engine) latency
and O(QPS x num_engines) probe traffic. LMCache's controller solves this with
an event-driven index (PAPERS.md), and BanaServe's unified cluster KV view
argues the same push design: engines publish cache mutations once, lookups
are answered from an index with zero engine traffic.

This module is the engine half of that protocol:

- `KVEventLog`: a bounded, thread-safe buffer of monotonically-sequenced
  cache mutations. `KVBlockPool` emits into it from the step thread (block
  admitted / block no longer locally matchable / cache cleared); the
  publisher drains it from the asyncio loop. Overflow drops the OLDEST
  events — the sequence numbering turns the drop into a visible gap the
  subscriber answers with a resync request, never a silently wrong index.

- `KVEventPublisher`: a background task owned by the engine server. It
  flushes batched events to the controller (`POST /kv/events`) on a short
  interval and falls back to a full snapshot (every currently matchable
  hash, taken under the engine lock) whenever the controller reports a
  sequence gap, the epoch changed (pool rebuild), or the connection was
  down — the classic event-sourcing "resync on reconnect" contract.

Wire format (one POST body):
    {"engine": "<base url>", "epoch": "<uuid>", "block_size": 16,
     "seq_start": 17, "events": [["a", "<hash hex>", "<parent hex>"],
                                 ["e", "<hash hex>"], ["c"]]}
or, for a snapshot:
    {"engine": ..., "epoch": ..., "block_size": ..., "snapshot": true,
     "seq": 42, "hashes": ["<hex>", ...]}

Hashes travel as hex strings: they are 128-bit chain hashes
(engine/kv_cache.py) and many JSON parsers mangle >64-bit ints.
"""

from __future__ import annotations

import asyncio
import threading
import time
import uuid
from collections import deque

from ..utils.logging import init_logger

logger = init_logger(__name__)

# ops, kept single-char: event batches are on the wire every flush interval
ADMIT = "a"
EVICT = "e"
CLEAR = "c"

DEFAULT_CAPACITY = 65536
DEFAULT_FLUSH_INTERVAL_S = 0.5
MAX_EVENTS_PER_POST = 8192
# an idle engine (no cache churn) posts an empty batch this often so the
# subscriber's liveness TTL (kv_index.DEFAULT_STALE_AFTER_S) can tell
# "quiet" from "dead" — a crashed publisher must stop winning lookups
HEARTBEAT_INTERVAL_S = 2.0


class KVEventLog:
    """Bounded buffer of sequenced KV cache events for ONE pool.

    Thread-safe: the pool emits from the engine step thread while the
    publisher drains from the asyncio loop. `epoch` identifies this pool
    incarnation — a subscriber seeing a new epoch must resync.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.epoch = uuid.uuid4().hex
        self.capacity = capacity
        self._lock = threading.Lock()
        # (seq, event, emit wall-time). The timestamp rides the wire so
        # subscribers can measure publish→apply convergence lag
        # (fleet.ConvergenceMeter) including in-buffer dwell, not just the
        # POST hop.
        self._buf: deque[tuple[int, tuple, float]] = deque()
        self._seq = 0  # seq of the most recently emitted event

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def pending_depth(self) -> int:
        """Events buffered awaiting flush — the publisher-vantage backlog
        gauge (tpu:kv_event_pending_queue_depth). A depth pinned at
        capacity means the publisher can't keep up (or is down) and the
        subscriber is about to see an overflow gap."""
        with self._lock:
            return len(self._buf)

    def _emit(self, event: tuple) -> None:
        with self._lock:
            self._seq += 1
            self._buf.append((self._seq, event, time.time()))
            if len(self._buf) > self.capacity:
                # drop oldest: the seq gap is detected by the subscriber
                # (and by the publisher's own continuity check) -> resync
                self._buf.popleft()

    def emit_admit(self, h: int, parent: int) -> None:
        self._emit((ADMIT, f"{h:x}", f"{parent:x}"))

    def emit_evict(self, h: int) -> None:
        self._emit((EVICT, f"{h:x}"))

    def emit_clear(self) -> None:
        self._emit((CLEAR,))

    def drain(self, max_events: int = MAX_EVENTS_PER_POST):
        """Pop up to max_events buffered events. Returns (seq_start, events)
        — events is [] when nothing is buffered. seq_start is the sequence
        number of the FIRST returned event; a caller tracking the last seq
        it shipped can detect overflow drops (seq_start jumped) and resync."""
        seq_start, events, _ = self.drain_timed(max_events)
        return seq_start, events

    def drain_timed(self, max_events: int = MAX_EVENTS_PER_POST):
        """drain() plus the emit wall-time of the OLDEST returned event
        (None when the batch is empty) — the publish timestamp the wire
        payload carries for convergence-lag measurement."""
        with self._lock:
            if not self._buf:
                return self._seq + 1, [], None
            n = min(max_events, len(self._buf))
            first_seq = self._buf[0][0]
            oldest_ts = self._buf[0][2]
            events = [self._buf.popleft()[1] for _ in range(n)]
            return first_seq, events, oldest_ts

    def snapshot_barrier(self) -> int:
        """Discard everything buffered and return the current seq — called
        with the pool quiesced (engine lock held) while the caller captures
        the full hash set. Buffered events are baked into that snapshot, so
        shipping them afterwards would double-apply."""
        with self._lock:
            self._buf.clear()
            return self._seq


class KVEventPublisher:
    """Flushes one engine's KVEventLog to the cluster KV index subscriber
    (KV controller, or a router in embedded-index mode)."""

    def __init__(
        self,
        controller_url: str,
        engine_url: str,
        log: KVEventLog,
        snapshot_fn,
        block_size: int,
        session_factory,
        interval_s: float = DEFAULT_FLUSH_INTERVAL_S,
        headers: dict | None = None,
    ):
        """snapshot_fn: async callable -> (epoch, seq, list[int] hashes),
        taken consistently (under the engine lock). session_factory: zero-arg
        callable returning the shared aiohttp.ClientSession. headers: extra
        request headers, e.g. the bearer key a keyed subscriber requires."""
        self.controller_url = controller_url.rstrip("/")
        self.headers = headers or {}
        self.engine_url = engine_url
        self.log = log
        self._snapshot_fn = snapshot_fn
        self.block_size = block_size
        self._session_factory = session_factory
        self.interval_s = interval_s
        self._need_snapshot = True  # first contact always resyncs
        self._last_sent_seq = 0
        self._last_post_t = 0.0  # monotonic time of the last successful POST
        self._task: asyncio.Task | None = None
        # counters for /debug + tests + the publisher-health contract
        # names (tpu:kv_event_publish_{batches,failures}_total — `posts`
        # is the batches counter: every successful POST incl. heartbeats
        # and snapshots)
        self.posts = 0
        self.events_sent = 0
        self.snapshots_sent = 0
        self.publish_failures = 0

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.flush()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep publishing through faults
                # flush() marks _need_snapshot itself when drained events
                # were actually lost; a failed heartbeat or snapshot POST
                # loses nothing, so don't force a full resync here
                self.publish_failures += 1
                logger.debug("kv event flush failed: %s", e)
            await asyncio.sleep(self.interval_s)

    async def _post(self, payload: dict) -> dict:
        sess = self._session_factory()
        async with sess.post(
            self.controller_url + "/kv/events", json=payload,
            headers=self.headers,
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(f"controller returned HTTP {resp.status}")
            self.posts += 1
            self._last_post_t = time.monotonic()
            return await resp.json()

    async def flush(self) -> None:
        """One publish round: snapshot if needed, else drain-and-send every
        buffered batch. Raises on transport faults; a full resync is queued
        only when drained events were actually lost in flight — failed
        heartbeats/snapshots lose nothing and just retry next round."""
        if self._need_snapshot:
            epoch, seq, hashes = await self._snapshot_fn()
            data = await self._post({
                "engine": self.engine_url,
                "epoch": epoch,
                "block_size": self.block_size,
                "snapshot": True,
                "seq": seq,
                "hashes": [f"{h:x}" for h in hashes],
                "ts": time.time(),
            })
            if data.get("resync") or data.get("status") == "error":
                raise RuntimeError(
                    f"controller rejected snapshot: {data.get('error') or data}"
                )
            self.snapshots_sent += 1
            self._last_sent_seq = seq
            self._need_snapshot = False
        while True:
            seq_start, events, oldest_ts = self.log.drain_timed()
            if not events:
                if (
                    time.monotonic() - self._last_post_t
                    >= HEARTBEAT_INTERVAL_S
                ):
                    # liveness heartbeat: an empty in-sequence batch — the
                    # subscriber applies nothing but refreshes last_event_t
                    data = await self._post({
                        "engine": self.engine_url,
                        "epoch": self.log.epoch,
                        "block_size": self.block_size,
                        "seq_start": self._last_sent_seq + 1,
                        "events": [],
                        "ts": time.time(),
                    })
                    if data.get("resync"):  # e.g. subscriber restarted
                        self._need_snapshot = True
                return
            if seq_start != self._last_sent_seq + 1:
                # local overflow dropped events between flushes — the index
                # is unrecoverable from the buffer; resync next round
                self._need_snapshot = True
                return
            try:
                data = await self._post({
                    "engine": self.engine_url,
                    "epoch": self.log.epoch,
                    "block_size": self.block_size,
                    "seq_start": seq_start,
                    "events": events,
                    # emit time of the OLDEST event in the batch: the
                    # subscriber's publish→apply lag measurement covers
                    # in-buffer dwell, not just the POST hop
                    "ts": oldest_ts,
                })
            except Exception:
                # these events left the log buffer and never arrived — the
                # subscriber's slice is now unrecoverable without a resync
                self._need_snapshot = True
                raise
            self.events_sent += len(events)
            self._last_sent_seq = seq_start + len(events) - 1
            if data.get("resync"):
                self._need_snapshot = True
                return
