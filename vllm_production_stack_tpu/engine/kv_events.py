"""Engine-side KV event stream: the push half of the cluster KV index.

KV-aware routing used to be pull-based: every routed request made the KV
controller fan a /kv/lookup probe out to EVERY engine, each probe tokenizing
the prompt and walking the hash chain server-side — O(slowest engine) latency
and O(QPS x num_engines) probe traffic. LMCache's controller solves this with
an event-driven index (PAPERS.md), and BanaServe's unified cluster KV view
argues the same push design: engines publish cache mutations once, lookups
are answered from an index with zero engine traffic.

This module is the engine half of that protocol:

- `KVEventLog`: a bounded, thread-safe buffer of monotonically-sequenced
  cache mutations. `KVBlockPool` emits into it from the step thread (block
  admitted / block no longer locally matchable / cache cleared); the
  publisher drains it from the asyncio loop. Overflow drops the OLDEST
  events — the sequence numbering turns the drop into a visible gap the
  subscriber answers with a resync request, never a silently wrong index.

- `KVEventPublisher`: a background task owned by the engine server. It
  flushes batched events to EVERY registered subscriber (`POST /kv/events`)
  on a short jittered interval. Subscribers advance independently: each one
  keeps its own publish cursor and snapshot-resync state, so a cold router
  replica joining the fleet (or one that dropped a batch) heals through a
  full snapshot addressed to it alone while in-sync subscribers keep
  receiving incremental batches — the fan-out half of ROADMAP 1's
  multi-replica routing (docs/34-fleet-routing.md). A resync is requested
  whenever that subscriber reports a sequence gap, the epoch changed (pool
  rebuild), or its connection was down while drained events were in flight
  — the classic event-sourcing "resync on reconnect" contract.

Wire format (one POST body):
    {"engine": "<base url>", "epoch": "<uuid>", "block_size": 16,
     "seq_start": 17, "events": [["a", "<hash hex>", "<parent hex>"],
                                 ["e", "<hash hex>"], ["c"]]}
or, for a snapshot:
    {"engine": ..., "epoch": ..., "block_size": ..., "snapshot": true,
     "seq": 42, "hashes": ["<hex>", ...]}

Hashes travel as hex strings: they are 128-bit chain hashes
(engine/kv_cache.py) and many JSON parsers mangle >64-bit ints.
"""

from __future__ import annotations

import asyncio
import threading
import time
import uuid
from collections import deque

from ..utils.logging import init_logger
from ..utils.system import jittered_interval

logger = init_logger(__name__)

# ops, kept single-char: event batches are on the wire every flush interval
ADMIT = "a"
EVICT = "e"
CLEAR = "c"

DEFAULT_CAPACITY = 65536
DEFAULT_FLUSH_INTERVAL_S = 0.5
MAX_EVENTS_PER_POST = 8192
# ±fraction of the flush interval each sleep is jittered by, so M router
# replicas × E engines never converge on synchronized publish ticks (the
# thundering-herd failure mode on a shared subscriber)
DEFAULT_JITTER_FRAC = 0.15
# per-POST bound: a blackholed subscriber (rescheduled pod, dead IP) must
# cost its OWN pipeline at most this long per round, never the shared
# session's full connect/total timeout — the log buffer only has to ride
# out this window before healthy subscribers would see an overflow gap
DEFAULT_SEND_TIMEOUT_S = 10.0
# failed snapshot attempts back off exponentially (per subscriber) up to
# this ceiling: capturing a snapshot costs O(pool) work under the engine
# lock, and a PERMANENTLY dead subscriber in the fan-out list must not
# tax the engine's hot path every flush round forever
SNAPSHOT_BACKOFF_MAX_S = 30.0
# an idle engine (no cache churn) posts an empty batch this often so the
# subscriber's liveness TTL (kv_index.DEFAULT_STALE_AFTER_S) can tell
# "quiet" from "dead" — a crashed publisher must stop winning lookups
HEARTBEAT_INTERVAL_S = 2.0


class KVEventLog:
    """Bounded buffer of sequenced KV cache events for ONE pool.

    Thread-safe: the pool emits from the engine step thread while the
    publisher drains from the asyncio loop. `epoch` identifies this pool
    incarnation — a subscriber seeing a new epoch must resync.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.epoch = uuid.uuid4().hex
        self.capacity = capacity
        self._lock = threading.Lock()
        # (seq, event, emit wall-time). The timestamp rides the wire so
        # subscribers can measure publish→apply convergence lag
        # (fleet.ConvergenceMeter) including in-buffer dwell, not just the
        # POST hop.
        self._buf: deque[tuple[int, tuple, float]] = deque()
        self._seq = 0  # seq of the most recently emitted event

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def pending_depth(self) -> int:
        """Events buffered awaiting flush — the publisher-vantage backlog
        gauge (tpu:kv_event_pending_queue_depth). A depth pinned at
        capacity means the publisher can't keep up (or is down) and the
        subscriber is about to see an overflow gap."""
        with self._lock:
            return len(self._buf)

    def _emit(self, event: tuple) -> None:
        with self._lock:
            self._seq += 1
            self._buf.append((self._seq, event, time.time()))
            if len(self._buf) > self.capacity:
                # drop oldest: the seq gap is detected by the subscriber
                # (and by the publisher's own continuity check) -> resync
                self._buf.popleft()

    def emit_admit(self, h: int, parent: int) -> None:
        self._emit((ADMIT, f"{h:x}", f"{parent:x}"))

    def emit_evict(self, h: int) -> None:
        self._emit((EVICT, f"{h:x}"))

    def emit_clear(self) -> None:
        self._emit((CLEAR,))

    def drain(self, max_events: int = MAX_EVENTS_PER_POST):
        """Pop up to max_events buffered events. Returns (seq_start, events)
        — events is [] when nothing is buffered. seq_start is the sequence
        number of the FIRST returned event; a caller tracking the last seq
        it shipped can detect overflow drops (seq_start jumped) and resync."""
        seq_start, events, _ = self.drain_timed(max_events)
        return seq_start, events

    def drain_timed(self, max_events: int = MAX_EVENTS_PER_POST):
        """drain() plus the emit wall-time of the OLDEST returned event
        (None when the batch is empty) — the publish timestamp the wire
        payload carries for convergence-lag measurement."""
        with self._lock:
            if not self._buf:
                return self._seq + 1, [], None
            n = min(max_events, len(self._buf))
            first_seq = self._buf[0][0]
            oldest_ts = self._buf[0][2]
            events = [self._buf.popleft()[1] for _ in range(n)]
            return first_seq, events, oldest_ts

    def snapshot_mark(self) -> int:
        """Current seq for a consistent snapshot — called with the pool
        quiesced (engine lock held) while the caller captures the full hash
        set. The buffer is deliberately NOT cleared: with fan-out, other
        subscribers may still need the buffered events, and the publisher's
        per-subscriber cursors skip anything at or below a subscriber's
        snapshot seq so nothing double-applies."""
        with self._lock:
            return self._seq


class _SubscriberState:
    """One subscriber's publish cursor. Each subscriber resyncs and
    advances independently, so a cold/failing replica never forces the
    in-sync ones through a snapshot — per-subscriber batching/resync is
    what makes publisher fan-out safe (docs/34-fleet-routing.md)."""

    __slots__ = ("url", "need_snapshot", "last_sent_seq", "last_post_t",
                 "posts", "events_sent", "snapshots_sent",
                 "publish_failures", "last_error", "snapshot_backoff_s",
                 "next_snapshot_t")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.need_snapshot = True  # first contact always resyncs
        self.last_sent_seq = 0
        self.last_post_t = 0.0  # monotonic time of the last successful POST
        self.posts = 0
        self.events_sent = 0
        self.snapshots_sent = 0
        self.publish_failures = 0
        self.last_error: str | None = None
        # failed-snapshot backoff (0 = try on the next round): a dead
        # subscriber's O(pool) snapshot capture must not recur every flush
        self.snapshot_backoff_s = 0.0
        self.next_snapshot_t = 0.0

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "need_snapshot": self.need_snapshot,
            "last_sent_seq": self.last_sent_seq,
            "posts": self.posts,
            "events_sent": self.events_sent,
            "snapshots_sent": self.snapshots_sent,
            "publish_failures": self.publish_failures,
            "last_error": self.last_error,
        }


class KVEventPublisher:
    """Flushes one engine's KVEventLog to every cluster KV index subscriber
    (the KV controller, router replicas in embedded-index mode, or both)."""

    def __init__(
        self,
        subscriber_urls: str | list[str],
        engine_url: str,
        log: KVEventLog,
        snapshot_fn,
        block_size: int,
        session_factory,
        interval_s: float = DEFAULT_FLUSH_INTERVAL_S,
        headers: dict | None = None,
        jitter_frac: float = DEFAULT_JITTER_FRAC,
        send_timeout_s: float = DEFAULT_SEND_TIMEOUT_S,
        heartbeat=None,
    ):
        """subscriber_urls: one base URL, a comma-separated string, or a
        list — every subscriber gets every batch, each with its own resync
        state. snapshot_fn: async callable -> (epoch, seq, list[int]
        hashes), taken consistently (under the engine lock).
        session_factory: zero-arg callable returning the shared
        aiohttp.ClientSession. headers: extra request headers, e.g. the
        bearer key a keyed subscriber requires."""
        if isinstance(subscriber_urls, str):
            subscriber_urls = [
                u.strip() for u in subscriber_urls.split(",") if u.strip()
            ]
        # normalize + dedupe: the same endpoint listed twice (comma-list
        # typo, trailing-slash variant) would mean two cursors fighting
        # over its ONE per-engine seq view — every round the second
        # arrival reads as a gap and the entry ping-pongs stale/resynced
        self.subscribers = [
            _SubscriberState(u)
            for u in dict.fromkeys(u.rstrip("/") for u in subscriber_urls)
        ]
        self.headers = headers or {}
        self.engine_url = engine_url
        self.log = log
        self._snapshot_fn = snapshot_fn
        self.block_size = block_size
        self._session_factory = session_factory
        self.interval_s = interval_s
        self.jitter_frac = jitter_frac
        self.send_timeout_s = send_timeout_s
        self._task: asyncio.Task | None = None
        # thread-liveness heartbeat (docs/37-flight-recorder.md,
        # flightrec.ThreadRegistry "kv_event_publisher"): beaten once per
        # publish round — a round stuck behind a blackholed subscriber
        # (or a starved event loop) stops beating and the watchdog names
        # this loop instead of the symptom (controller-side resync storms)
        self.heartbeat = heartbeat
        # flush-loop faults not attributable to one subscriber (e.g. the
        # snapshot_fn itself); per-subscriber transport faults land on the
        # subscriber's own counter and both roll up in publish_failures
        self._loop_failures = 0

    # -- aggregate counters (metrics contract names keep reading the same
    # publisher-vantage totals whether one subscriber is configured or M:
    # tpu:kv_event_publish_{batches,failures}_total) ----------------------

    @property
    def posts(self) -> int:
        """Successful POSTs across all subscribers (incl. heartbeats and
        snapshots) — the batches counter."""
        return sum(s.posts for s in self.subscribers)

    @property
    def events_sent(self) -> int:
        return sum(s.events_sent for s in self.subscribers)

    @property
    def snapshots_sent(self) -> int:
        return sum(s.snapshots_sent for s in self.subscribers)

    @property
    def publish_failures(self) -> int:
        return self._loop_failures + sum(
            s.publish_failures for s in self.subscribers
        )

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _next_interval(self) -> float:
        """The next sleep, jittered so engines never POST to the shared
        subscribers on synchronized ticks (utils.system.jittered_interval
        is the one shared herd-avoidance policy)."""
        return jittered_interval(self.interval_s, self.jitter_frac)

    async def _run(self) -> None:
        hb = self.heartbeat
        while True:
            try:
                if hb is not None:
                    hb.beat()  # a hung flush round stops the beats
                await self.flush()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep publishing through faults
                # per-subscriber transport faults are handled inside
                # flush(); whatever reaches here (snapshot_fn and other
                # shared-path faults) loses no subscriber-attributed events
                self._loop_failures += 1
                logger.debug("kv event flush failed: %s", e)
            if hb is not None:
                hb.idle()  # the inter-round sleep is parked, not stalled
            await asyncio.sleep(self._next_interval())

    async def _post(self, sub: _SubscriberState, payload: dict) -> dict:
        sess = self._session_factory()
        async with sess.post(
            sub.url + "/kv/events", json=payload, headers=self.headers,
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(f"subscriber returned HTTP {resp.status}")
            sub.posts += 1
            sub.last_post_t = time.monotonic()
            return await resp.json()

    async def flush(self) -> None:
        """One publish round. The shared log is drained ONCE into this
        round's batch list; every subscriber then runs its OWN send
        pipeline concurrently (snapshot if owed — one capture serves them
        all — then the batches, then a heartbeat if idle), so a slow or
        blackholed subscriber never head-of-line blocks delivery to the
        healthy ones: it can only stretch the round's tail, and each POST
        is additionally bounded by send_timeout_s. Per-subscriber faults
        never raise — they mark only that subscriber for resync (a failed
        heartbeat or snapshot loses nothing and just retries next round; a
        failed event batch lost those events FOR THAT SUBSCRIBER and owes
        it a snapshot)."""
        snapshot = None
        now = time.monotonic()
        if any(
            s.need_snapshot and now >= s.next_snapshot_t
            for s in self.subscribers
        ):
            # capture only when an owed subscriber's attempt is actually
            # due (failed attempts back off): the O(pool) capture under
            # the engine lock must not recur every round for a dead URL
            epoch, seq, hashes = await self._snapshot_fn()
            snapshot = (epoch, seq, [f"{h:x}" for h in hashes])
        batches = []
        while True:
            seq_start, events, oldest_ts = self.log.drain_timed()
            if not events:
                break
            batches.append((seq_start, events, oldest_ts))
        now = time.monotonic()
        await asyncio.gather(*(
            self._subscriber_round(s, snapshot, batches, now)
            for s in self.subscribers
        ))

    async def _subscriber_round(
        self, sub: _SubscriberState, snapshot, batches: list, now: float,
    ) -> None:
        if (
            sub.need_snapshot and snapshot is not None
            and now >= sub.next_snapshot_t
        ):
            await self._send_snapshot(sub, *snapshot)
        for seq_start, events, oldest_ts in batches:
            await self._send_batch(sub, seq_start, events, oldest_ts)
        if (
            not sub.need_snapshot
            and now - sub.last_post_t >= HEARTBEAT_INTERVAL_S
        ):
            await self._send_heartbeat(sub)

    async def _send_snapshot(
        self, sub: _SubscriberState, epoch: str, seq: int,
        hex_hashes: list[str],
    ) -> None:
        try:
            data = await asyncio.wait_for(self._post(sub, {
                "engine": self.engine_url,
                "epoch": epoch,
                "block_size": self.block_size,
                "snapshot": True,
                "seq": seq,
                "hashes": hex_hashes,
                "ts": time.time(),
            }), self.send_timeout_s)
            if data.get("resync") or data.get("status") == "error":
                raise RuntimeError(
                    f"subscriber rejected snapshot: "
                    f"{data.get('error') or data}"
                )
        except Exception as e:
            # nothing was lost — the snapshot retries after a per-
            # subscriber exponential backoff (a permanently dead URL must
            # not re-trigger the O(pool) capture every round)
            sub.publish_failures += 1
            sub.last_error = f"{type(e).__name__}: {e}"
            sub.snapshot_backoff_s = min(
                SNAPSHOT_BACKOFF_MAX_S,
                max(self.interval_s, 2 * sub.snapshot_backoff_s),
            )
            sub.next_snapshot_t = time.monotonic() + sub.snapshot_backoff_s
            logger.debug("kv snapshot to %s failed: %s", sub.url, e)
            return
        sub.snapshots_sent += 1
        sub.last_sent_seq = seq
        sub.need_snapshot = False
        sub.last_error = None
        sub.snapshot_backoff_s = 0.0
        sub.next_snapshot_t = 0.0

    async def _send_batch(
        self, sub: _SubscriberState, seq_start: int, events: list,
        oldest_ts: float | None,
    ) -> None:
        if sub.need_snapshot:
            return  # resync pending; batches resume after its snapshot
        if seq_start > sub.last_sent_seq + 1:
            # local overflow dropped events between this subscriber's
            # cursor and the batch — its slice is unrecoverable from the
            # buffer; resync next round
            sub.need_snapshot = True
            return
        # events at or below the cursor are already baked into this
        # subscriber's snapshot (the log's snapshot_mark doesn't clear the
        # shared buffer) or were delivered in an earlier round — skip them
        skip = sub.last_sent_seq + 1 - seq_start
        if skip >= len(events):
            return
        try:
            data = await asyncio.wait_for(self._post(sub, {
                "engine": self.engine_url,
                "epoch": self.log.epoch,
                "block_size": self.block_size,
                "seq_start": seq_start + skip,
                # emit time of the OLDEST event in the DRAINED batch: lag
                # covers in-buffer dwell; for a sliced batch it slightly
                # overestimates (rare: only right after a snapshot)
                "ts": oldest_ts,
                "events": events[skip:],
            }), self.send_timeout_s)
        except Exception as e:
            # these events left the shared buffer and never arrived HERE —
            # only this subscriber's slice needs the snapshot
            sub.need_snapshot = True
            sub.publish_failures += 1
            sub.last_error = f"{type(e).__name__}: {e}"
            logger.debug("kv event batch to %s failed: %s", sub.url, e)
            return
        sub.events_sent += len(events) - skip
        sub.last_sent_seq = seq_start + len(events) - 1
        sub.last_error = None
        if data.get("resync"):  # e.g. subscriber restarted / epoch change
            sub.need_snapshot = True

    async def _send_heartbeat(self, sub: _SubscriberState) -> None:
        try:
            data = await asyncio.wait_for(self._post(sub, {
                "engine": self.engine_url,
                "epoch": self.log.epoch,
                "block_size": self.block_size,
                "seq_start": sub.last_sent_seq + 1,
                "events": [],
                "ts": time.time(),
            }), self.send_timeout_s)
        except Exception as e:
            # a failed heartbeat loses nothing; no resync owed
            sub.publish_failures += 1
            sub.last_error = f"{type(e).__name__}: {e}"
            logger.debug("kv heartbeat to %s failed: %s", sub.url, e)
            return
        if data.get("resync"):
            sub.need_snapshot = True

    def debug_snapshot(self) -> dict:
        """Per-subscriber cursor view for /debug introspection."""
        return {
            "interval_s": self.interval_s,
            "jitter_frac": self.jitter_frac,
            "subscribers": [s.snapshot() for s in self.subscribers],
        }
