"""HBM KV page pool: allocator + content-addressed prefix cache.

The reference delegates paged-KV management to vLLM and exposes only its
metrics (`vllm:gpu_cache_usage_perc`, `vllm:gpu_prefix_cache_*` — scraped by
the router, src/vllm_router/stats/engine_stats.py:63-76). This module is the
TPU engine's equivalent: host-side bookkeeping for the device-side paged pool
(the actual pages live in one stacked jax.Array, models/llama.py
init_kv_cache). Block 0 is the reserved null page (ops/attention.py).

Prefix caching is content-addressed like vLLM's: a *full* block's identity is
the rolling hash of (parent block hash, its tokens). Blocks whose refcount
drops to zero are not returned to the free list immediately — they park in an
LRU of evictable cached blocks, so a new request with a shared prefix can
re-reference their KV without recompute. The hit/query counters back the
`prefix_cache_hit_rate` metric contract.
"""

from __future__ import annotations

import hashlib
import logging
from collections import OrderedDict, deque
from dataclasses import dataclass

import numpy as np

from .kv_codec import decode_block, logical_shape

logger = logging.getLogger(__name__)

_ROOT_HASH = 0x9E3779B97F4A7C15


def chain_hash(parent: int, tokens: tuple[int, ...]) -> int:
    """Collision-resistant rolling block hash. Python's hash() would make
    wrong-KV collisions constructible (even adversarially, in a multi-tenant
    server); a truncated sha256 over parent||tokens removes that."""
    h = hashlib.sha256(int(parent).to_bytes(16, "little", signed=False))
    h.update(b"".join(int(t).to_bytes(8, "little", signed=True) for t in tokens))
    return int.from_bytes(h.digest()[:16], "little")


def chain_hash_run(
    parent: int, token_ids: list[int], block_size: int
) -> list[int]:
    """Chain hash of every FULL block of `token_ids`, in order, rooted at
    `parent` — the single definition of block identity. The pool's `_chain`
    and the cluster KV index (kv_index.py) both delegate here, so an indexed
    /lookup can never diverge from what a real match would reuse. Uses the
    native batch hasher (csrc/kvhash.cpp via utils/native.py) when available,
    one Python sha256 round-trip per block otherwise."""
    from ..utils.native import chain_hashes_native

    hashes = chain_hashes_native(parent, token_ids, block_size)
    if hashes is not None:
        return hashes
    out: list[int] = []
    for i in range(len(token_ids) // block_size):
        parent = chain_hash(
            parent, tuple(token_ids[i * block_size : (i + 1) * block_size])
        )
        out.append(parent)
    return out


@dataclass
class CacheStats:
    queries: int = 0  # full prompt blocks looked up
    hits: int = 0  # full prompt blocks served from cache

    @property
    def hit_rate(self) -> float:
        return self.hits / self.queries if self.queries else 0.0


class KVBlockPool:
    """Host-side accounting for the device page pool of ONE engine."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        host_tier=None,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the null page)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        # optional HostKVTier: evicted cached blocks offload HBM→host and
        # prefix matches continue into it (engine/kv_host_tier.py)
        self.host_tier = host_tier
        # event stream for the cluster KV index (engine/kv_events.py): every
        # transition of a hash's local matchability is published so the KV
        # controller/router can answer /lookup without probing this engine.
        # Always on with prefix caching — emission is a couple of deque ops
        # per NEW full block, nothing consumes it until a publisher attaches.
        self.events = None
        if enable_prefix_caching:
            from .kv_events import KVEventLog

            self.events = KVEventLog()
        if host_tier is not None:
            # ring and disk drops unpublish hashes that are no longer
            # matchable anywhere local (see _on_host_drop)
            host_tier.on_drop = self._on_host_drop
            if getattr(host_tier, "disk", None) is not None:
                host_tier.disk.on_drop = self._on_host_drop
            # migration-aware ring eviction shares the pool's replica set
            # (bound method of the set object — survives in-place updates)
            host_tier.is_replicated = (
                lambda h: h in self._replicated
            )
        # page geometry remote fetches are validated against; the engine
        # sets this once the runner's pool exists (None = skip validation,
        # e.g. unit tests with no device pool)
        self.expected_block_shape: tuple[int, ...] | None = None
        # block 0 reserved as the null page
        self._free: deque[int] = deque(range(1, num_blocks))
        self._ref: dict[int, int] = {}
        # content-addressing maps (full, computed blocks only)
        self._hash_to_block: dict[int, int] = {}
        self._block_to_hash: dict[int, int] = {}
        # refcount-0 cached blocks, LRU order (oldest first -> evicted first)
        self._evictable: OrderedDict[int, None] = OrderedDict()
        # migration-aware eviction (docs/39-device-peer-kv.md, ROADMAP 2b):
        # hashes the cluster's replication controller confirmed resident on
        # ≥1 PEER engine. Eviction prefers these — losing a replicated
        # block costs a peer pull, losing the cluster's last copy costs a
        # recompute. Advisory only: a stale entry just mis-orders victims.
        self._replicated: set[int] = set()
        self.stats = CacheStats()
        # per-block KV origin of the LAST match_prefix call ("hbm" | "host"
        # | "disk" | "remote", parallel to its return) — consumed by the
        # scheduler's hydration attribution before the next match runs
        self.last_match_sources: list[str] = []
        # blocks currently held by the scratch (non-content-addressed)
        # namespace — the draft proposer's pool share, for observability
        self.scratch_blocks = 0
        if enable_prefix_caching:
            # warm the native batch hasher NOW (pool construction = engine
            # init, where XLA compiles already dominate) — never lazily from
            # the admission path, where a cold g++ build would stall the
            # first request and everything queued behind it
            from ..utils.native import chain_hashes_native

            chain_hashes_native(_ROOT_HASH, [0] * block_size, block_size)

    # -- capacity ----------------------------------------------------------

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        """Blocks allocatable right now (free list + evictable cached)."""
        return len(self._free) + len(self._evictable)

    @property
    def usage_perc(self) -> float:
        """Fraction of pool actively referenced — the TPU analogue of
        vllm:gpu_cache_usage_perc."""
        return 1.0 - self.num_free / self.num_usable

    # -- allocation --------------------------------------------------------

    def allocate_scratch(self) -> int | None:
        """Allocate a block OUTSIDE the content-addressed namespace — the
        draft-model proposer's block-table rung (docs/36-speculative-
        decoding.md). Scratch blocks share the allocator and byte budget
        but are never registered: no hash chain ever points at one, so a
        draft page can never satisfy a prefix match, a /kv/lookup probe, a
        peer /kv/peer_contains walk, or a KV export — isolation is
        structural, not filtered. Freed via free_scratch."""
        blk = self.allocate()
        if blk is not None:
            self.scratch_blocks += 1
        return blk

    def free_scratch(self, blk: int) -> None:
        self.scratch_blocks -= 1
        self.free_block(blk)

    # oldest-end window scanned for a peer-replicated victim before the
    # plain LRU-oldest falls: bounds the preference at O(32) dict probes
    # per eviction, preserving allocate()'s hot-path cost
    _VICTIM_SCAN = 32

    def mark_replicated(self, hashes: list[int]) -> int:
        """Record that `hashes` are resident on ≥1 peer engine (the
        replication controller confirmed a copy landed — docs/39), making
        their blocks PREFERRED eviction victims here: the cluster keeps
        the copy either way, so this engine should shed them first and
        keep blocks whose only copy it holds. Returns how many are
        currently block-resident (diagnostic)."""
        if len(self._replicated) > 4 * self.num_blocks:
            # advisory set, bounded: replica hints outliving the pool many
            # times over carry no ordering signal worth the memory
            self._replicated.clear()
        self._replicated.update(hashes)
        return sum(1 for h in hashes if h in self._hash_to_block)

    def _pick_victim(self) -> int:
        """Next eviction victim: a peer-replicated block from the oldest
        _VICTIM_SCAN evictable entries when one exists, else LRU-oldest
        (migration-aware eviction, docs/39-device-peer-kv.md)."""
        if self._replicated:
            for i, b in enumerate(self._evictable):
                if i >= self._VICTIM_SCAN:
                    break
                if self._block_to_hash.get(b) in self._replicated:
                    del self._evictable[b]
                    return b
        blk, _ = self._evictable.popitem(last=False)
        return blk

    def allocate(self) -> int | None:
        if self._free:
            blk = self._free.popleft()
        elif self._evictable:
            blk = self._pick_victim()
            h = self._block_to_hash.pop(blk)
            self._hash_to_block.pop(h, None)
            if self.host_tier is not None:
                # offload BEFORE the block id is handed out for reuse — the
                # device executes in dispatch order, so the host copy reads
                # the old pages even though the fetch is asynchronous
                self.host_tier.store(h, blk)
            elif self.events is not None:
                # no host tier: the hash stopped being matchable here (with
                # a host tier it stays matchable in the ring; the ring's own
                # drop hook emits the evict when it truly leaves)
                self.events.emit_evict(h)
        else:
            return None
        self._ref[blk] = 1
        return blk

    def free_block(self, blk: int) -> None:
        ref = self._ref.get(blk)
        if ref is None:
            raise KeyError(f"double free of block {blk}")
        if ref > 1:
            self._ref[blk] = ref - 1
            return
        del self._ref[blk]
        if blk in self._block_to_hash:
            self._evictable[blk] = None  # parked, content still addressable
        else:
            self._free.append(blk)

    # -- prefix caching ----------------------------------------------------

    def _chain(self, token_ids: list[int], parent: int):
        """Chain hash of each FULL block of the prompt, in order — shared by
        match_prefix and match_length via module-level `chain_hash_run`."""
        return chain_hash_run(parent, token_ids, self.block_size)

    def match_prefix(
        self, token_ids: list[int], parent: int | None = None,
        limit_blocks: int | None = None,
    ) -> list[int]:
        """Longest run of cached full blocks matching the prompt's head —
        HBM-resident blocks first, then continuing into the host tier (each
        host hit uploads into a freshly allocated HBM block). Acquires a
        reference on every matched block. `parent` is the chain root — the
        scheduler salts it per LoRA adapter so base and adapter KV never
        cross-match (their K/V bytes differ when k/v projections carry
        deltas).

        `limit_blocks` caps the match (hydration planner admissions: the
        scheduler consumes the leading HBM/host-ring run synchronously and
        plans the disk/remote remainder as async chunk loads instead of
        blocking here — docs/31-hydration-planner.md).

        Hydration attribution (docs/30-kv-flow-telemetry.md): alongside
        the matched blocks, `last_match_sources` records where each came
        from — "hbm" | "host" | "disk" | "remote", parallel to the return
        value — so the scheduler can classify the request's prompt tokens
        by KV origin exactly once at admission."""
        matched: list[int] = []
        self.last_match_sources = sources = []
        if not self.enable_prefix_caching:
            return matched
        if limit_blocks is not None:
            # hash only what the cap can match — the planner admission
            # already chained the full prompt once in probe_prefix
            token_ids = token_ids[: limit_blocks * self.block_size]
        hashes = list(
            self._chain(token_ids, _ROOT_HASH if parent is None else parent)
        )
        for idx, h in enumerate(hashes):
            self.stats.queries += 1
            blk = self._hash_to_block.get(h)
            if blk is None:
                blk, source = self._reload_from_host(h)
                if blk is None:
                    if limit_blocks is not None:
                        break  # planner admission: never block on remote
                    # both local tiers miss: continue the chain into the
                    # remote store (one batched mget for the remainder)
                    remote = self._match_remote(hashes[idx:])
                    matched.extend(remote)
                    sources.extend(["remote"] * len(remote))
                    break
            else:
                self._acquire(blk)
                source = "hbm"
            self.stats.hits += 1
            matched.append(blk)
            sources.append(source)
        return matched

    def _match_remote(self, hashes: list[int]) -> list[int]:
        """Fetch the consecutive remote-held prefix of `hashes` into freshly
        allocated HBM blocks (cross-engine KV reuse — the LMCache-server
        capability). Fetched blocks are promoted into the host ring so the
        next match stays local. queries for hashes[0] was already counted by
        the caller; the rest count here.

        Mirrors import_blocks: geometry is validated against the engine's
        page shape (a version-skewed remote store degrades to a miss, never
        a corrupt match), and the hash→block mappings + hit counts commit
        only AFTER the batched device upload succeeds — a failed upload frees
        the staged blocks instead of leaving hashes pointing at pages whose
        KV was never written."""
        remote = getattr(self.host_tier, "remote", None)
        if remote is None:
            return []
        want = self.expected_block_shape
        staged: list = []  # (hash, blk, data) for ONE batched device upload
        for i, (h, data) in enumerate(zip(hashes, remote.fetch_run(hashes))):
            if i > 0:
                self.stats.queries += 1
            # geometry check on the LOGICAL shape: at-rest-encoded fetches
            # arrive as EncodedKVBlock (wire form) and carry their decoded
            # shape in metadata
            if want is not None and logical_shape(data) != tuple(want):
                logger.warning(
                    "remote KV block %x has shape %s, engine needs %s — "
                    "dropping the fetched run (version-skewed store?)",
                    h, logical_shape(data), want,
                )
                break
            blk = self.allocate()  # may evict (offload+write-through) others
            if blk is None:
                break
            staged.append((h, blk, data))
        if not staged:
            return []
        try:
            # one dispatch for the whole fetched run — per-block uploads
            # cost a device round trip each on high-RTT links. THIS is the
            # dequant-on-adopt boundary: encoded blocks decode here, right
            # before the device upload.
            self.host_tier.upload_many(
                [blk for _, blk, _ in staged],
                [decode_block(d) for _, _, d in staged],
            )
        except Exception:
            logger.exception(
                "remote KV upload failed — freeing %d staged blocks and "
                "degrading to a cache miss", len(staged)
            )
            for _, blk, _ in staged:
                self.free_block(blk)
            return []
        matched: list[int] = []
        for h, blk, data in staged:
            self._hash_to_block[h] = blk
            self._block_to_hash[blk] = h
            self.host_tier.insert_resolved(h, data)
            if self.events is not None:
                self.events.emit_admit(h, 0)  # parent unknown mid-chain
            self.stats.hits += 1
            matched.append(blk)
        return matched

    # -- hydration planner support (docs/31-hydration-planner.md) ----------

    def probe_prefix(
        self, token_ids: list[int], parent: int | None = None,
        local_only: bool = False, peer=None, owner_hint: str | None = None,
    ) -> tuple[list[int], list[str], str]:
        """(hashes, tiers, peer_owner) of the longest consecutively-
        resident run of full prompt blocks across EVERY tier, WITHOUT
        moving data, taking references, or touching the hit counters —
        the residency map the compute-or-load planner decides over.
        tiers[i] is "hbm" | "host" | "disk" | "remote" | "peer" |
        "device" (a peer continuation on a shared-mesh owner); the
        remote continuation is one batched contains round trip (no
        payload), same as match_length. `local_only` skips every round
        trip — the `off` kill switch must not keep a sick remote store
        (or peer) on the admission path.

        Peer continuation (docs/35-peer-kv-reuse.md): when the local +
        remote run ends short of the full chain and a `peer` client
        (engine/kv_peer.PeerKVTier) is supplied, the probe continues into
        ANOTHER ENGINE's tiers — the router's `owner_hint` names the
        owner directly (priced route-vs-migrate stamped it upstream), else
        one cluster-index lookup rediscovers it; either way one
        /kv/peer_contains round trip confirms the owner's ACTUAL
        consecutive residency (the index can be seconds stale).
        peer_owner is the confirmed owner URL, "" when the run has no
        peer tail."""
        if not self.enable_prefix_caching:
            return [], [], ""
        hashes = list(
            self._chain(token_ids, _ROOT_HASH if parent is None else parent)
        )
        tiers: list[str] = []
        for idx, h in enumerate(hashes):
            if h in self._hash_to_block:
                tiers.append("hbm")
                continue
            loc = (
                self.host_tier.location(h)
                if self.host_tier is not None
                else ""
            )
            if loc:
                tiers.append(loc)
                continue
            if not local_only:
                remote = getattr(self.host_tier, "remote", None)
                if remote is not None:
                    n = remote.contains_run(hashes[idx:])
                    tiers.extend(["remote"] * n)
            break
        peer_owner = ""
        start = len(tiers)
        # the cluster lookup is a synchronous round trip on the step
        # thread: only rediscover when the non-resident remainder is big
        # enough that a peer pull could plausibly beat recomputing it —
        # tiny tails aren't worth an admission-path hop (an explicit
        # router hint is trusted regardless: its round trip was already
        # paid at the router)
        MIN_LOOKUP_BLOCKS = 4
        if peer is not None and not local_only and start < len(hashes):
            owner = (owner_hint or "").rstrip("/")
            if not owner and len(hashes) - start >= MIN_LOOKUP_BLOCKS:
                owner, matched = peer.cluster_lookup(hashes, self.block_size)
                # the index answers from the chain ROOT: an owner whose
                # whole run is shorter than what this engine already has
                # locally adds nothing beyond `start`
                if owner and matched <= start:
                    owner = ""
            if owner:
                n = peer.contains_run(owner, hashes[start:])
                if n > 0:
                    # "device" when the owner negotiated the device-path
                    # transport (shared mesh — docs/39-device-peer-kv.md):
                    # same peer continuation, collective-priced tier label
                    tf = getattr(peer, "transport_for", None)
                    tiers.extend([tf(owner) if tf else "peer"] * n)
                    peer_owner = owner
        return hashes[: len(tiers)], tiers, peer_owner

    def adopt_planned_run(
        self, hashes: list[int], arrays: list
    ) -> list[int] | None:
        """Commit one LANDED hydration chunk: upload its fetched host-RAM
        bytes into freshly allocated HBM blocks and register them, taking
        a reference on every block for the adopting request (allocate()
        hands blocks out at refcount 1; a block that raced back into HBM
        is re-acquired instead of re-uploaded — its arrays slot may be
        None). All-or-nothing: any allocation/geometry/upload failure
        frees everything staged and returns None, and the scheduler falls
        back to recomputing the chunk. Same commit discipline as
        _match_remote: hash→block mappings land only AFTER the batched
        device upload succeeds."""
        want = self.expected_block_shape
        staged: list[tuple[int, int, object]] = []  # (hash, blk, data|None)
        for h, data in zip(hashes, arrays):
            existing = self._hash_to_block.get(h)
            if existing is not None:
                self._acquire(existing)
                staged.append((h, existing, None))
                continue
            if data is None or (
                want is not None
                and logical_shape(data) != tuple(want)
            ):
                # missing bytes (evicted hbm-tier block) or a version-
                # skewed remote payload: the chunk cannot adopt
                for _, blk, _ in staged:
                    self.free_block(blk)
                return None
            blk = self.allocate()
            if blk is None:
                for _, bl, _ in staged:
                    self.free_block(bl)
                return None
            staged.append((h, blk, data))
        uploads = [(blk, d) for _, blk, d in staged if d is not None]
        if uploads:
            try:
                # dequant-on-adopt: hydration chunks fetched from remote/
                # peer tiers land in wire form and decode only here, at
                # the device-upload boundary
                self.host_tier.upload_many(
                    [blk for blk, _ in uploads],
                    [decode_block(d) for _, d in uploads],
                )
            except Exception:
                logger.exception(
                    "hydration chunk upload failed — freeing %d staged "
                    "blocks and falling back to recompute", len(staged)
                )
                for _, blk, _ in staged:
                    self.free_block(blk)
                return None
        for h, blk, data in staged:
            if data is not None:
                self._hash_to_block[h] = blk
                self._block_to_hash[blk] = h
                # promote into the ring so the next match (and a
                # preempted resume) stays local
                self.host_tier.insert_resolved(h, data)
                if self.events is not None:
                    self.events.emit_admit(h, 0)  # parent unknown mid-chain
            self.stats.hits += 1
        return [blk for _, blk, _ in staged]

    # -- adoption staging (KV transfer, both transports) -------------------

    def stage_adoption(self, hashes: list[int]):
        """Allocate destination blocks for the non-resident members of a
        shipped hash run. Returns (staged, pinned): staged = [(hash, blk)]
        to fill and commit; pinned = already-resident blocks REF-PINNED for
        the duration — without the pin, a later allocate() in this same
        staging could evict a resident chain member, leaving the freshly
        adopted blocks unreachable behind a chain hole. Call exactly one of
        commit_adoption/abort_adoption afterwards. The ONE definition of
        adoption bookkeeping shared by the host-staged HTTP path
        (kv_transfer.import_blocks) and the device path
        (kv_device_transfer.ship_kv_device)."""
        staged: list[tuple[int, int]] = []
        pinned: list[int] = []
        for h in hashes:
            existing = self._hash_to_block.get(h)
            if existing is not None:
                self._acquire(existing)
                pinned.append(existing)
                continue
            blk = self.allocate()
            if blk is None:
                break
            staged.append((h, blk))
        return staged, pinned

    def commit_adoption(
        self, staged: list[tuple[int, int]], pinned: list[int]
    ) -> None:
        """Register filled blocks as content-addressable evictable cache."""
        for h, blk in staged:
            self._hash_to_block[h] = blk
            self._block_to_hash[blk] = h
            if self.events is not None:
                self.events.emit_admit(h, 0)  # parent unknown (adopted run)
            self.free_block(blk)  # park: refcount 0, addressable
        for blk in pinned:
            self.free_block(blk)

    def abort_adoption(
        self, staged: list[tuple[int, int]], pinned: list[int]
    ) -> None:
        for _, blk in staged:
            self.free_block(blk)
        for blk in pinned:
            self.free_block(blk)

    def _reload_from_host(self, h: int) -> tuple[int | None, str]:
        """Host-tier continuation of a prefix match: allocate an HBM block
        and upload hash h's offloaded pages into it. Returns (block, rung)
        where rung is "host" (ring hit) or "disk" (promoted off the disk
        tier) — the hydration-attribution distinction."""
        if self.host_tier is None or h not in self.host_tier:
            return None, ""
        blk = self.allocate()  # may itself evict (and offload) another block
        if blk is None:
            return None, ""
        source = self.host_tier.reload_into(h, blk)
        if not source:  # raced an eviction
            self.free_block(blk)
            return None, ""
        self._hash_to_block[h] = blk
        self._block_to_hash[blk] = h
        return blk, source

    def match_length(
        self, token_ids: list[int], parent: int | None = None
    ) -> int:
        """Matched-prefix length in TOKENS across both tiers, without taking
        references or moving any data — the /kv/lookup probe the KV-aware
        router depends on (reference: LMCache LookupMsg, routing_logic.py:
        222-344; gateway kv_aware_picker.go:90-133)."""
        if not self.enable_prefix_caching:
            return 0
        n = 0
        hashes = list(
            self._chain(token_ids, _ROOT_HASH if parent is None else parent)
        )
        for idx, h in enumerate(hashes):
            if h not in self._hash_to_block and (
                self.host_tier is None or h not in self.host_tier
            ):
                remote = getattr(self.host_tier, "remote", None)
                if remote is not None:
                    # continue the probe into the remote store: one batched
                    # contains round trip, no data movement
                    n += self.block_size * remote.contains_run(hashes[idx:])
                break
            n += self.block_size
        return n

    def _acquire(self, blk: int) -> None:
        if blk in self._ref:
            self._ref[blk] += 1
        else:
            self._ref[blk] = 1
            self._evictable.pop(blk, None)

    def register_full_block(
        self, blk: int, parent_hash: int, tokens: tuple[int, ...]
    ) -> int:
        """Make a freshly computed full block content-addressable. Returns the
        chain hash to use as the next block's parent."""
        h = chain_hash(parent_hash, tokens)
        if not self.enable_prefix_caching:
            return h
        if h not in self._hash_to_block:
            self._hash_to_block[h] = blk
            self._block_to_hash[blk] = h
            if self.events is not None and not (
                self.host_tier is not None and h in self.host_tier
            ):
                # a host-tier-resident hash is already published; re-entering
                # HBM changes nothing about cluster-level matchability
                self.events.emit_admit(h, parent_hash)
        return h

    @staticmethod
    def root_hash() -> int:
        return _ROOT_HASH

    def reset_stats(self) -> None:
        self.stats = CacheStats()

    def clear_prefix_cache(self) -> None:
        """Drop all content-addressing state. MUST be called whenever the
        device-side pool is reinitialized (sleep/wake, weight reload): the
        hashes describe KV bytes that no longer exist, and serving a match
        against a zeroed page would silently corrupt attention."""
        if self._ref:
            raise RuntimeError(
                "cannot clear prefix cache while blocks are referenced"
            )
        self._hash_to_block.clear()
        self._block_to_hash.clear()
        for blk in self._evictable:
            self._free.append(blk)
        self._evictable.clear()
        if self.events is not None:
            self.events.emit_clear()
            if self.host_tier is not None:
                # ring/disk entries survive the device-pool rebuild (bytes
                # are bytes, keyed by content hash) and stay matchable —
                # re-admit them so the cluster index's clear doesn't
                # under-report
                for h in self.host_tier.resident_hashes():
                    self.events.emit_admit(h, 0)

    # -- cluster KV index events (engine/kv_events.py) ---------------------

    def _on_host_drop(self, h: int) -> None:
        """The host ring or the disk tier dropped hash h: emit a cluster
        evict only if no local tier (HBM / ring / disk) still holds it —
        a ring→disk demotion or a drop of a copy keeps it matchable."""
        if (
            self.events is not None
            and h not in self._hash_to_block
            and (self.host_tier is None or h not in self.host_tier)
        ):
            self.events.emit_evict(h)

    def published_hashes(self) -> list[int]:
        """Every hash `match_length` would currently count from local tiers
        (HBM + host ring + disk) — the full-resync snapshot for the cluster
        KV index. Call with the pool quiesced (engine lock held)."""
        hashes = set(self._hash_to_block)
        if self.host_tier is not None:
            hashes.update(self.host_tier.resident_hashes())
        return list(hashes)

    def snapshot_events(self) -> tuple[str, int, list[int]]:
        """(epoch, seq, hashes) for a consistent index resync. The event
        buffer is NOT cleared — with publisher fan-out other subscribers
        may still need the buffered events; the publisher's per-subscriber
        cursors skip anything at or below `seq` for the subscriber this
        snapshot heals, so nothing double-applies. Call with the pool
        quiesced (engine lock held)."""
        if self.events is None:
            raise RuntimeError("prefix caching (and its event log) disabled")
        seq = self.events.snapshot_mark()
        return self.events.epoch, seq, self.published_hashes()
