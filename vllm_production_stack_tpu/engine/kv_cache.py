"""HBM KV page pool: allocator + content-addressed prefix cache.

The reference delegates paged-KV management to vLLM and exposes only its
metrics (`vllm:gpu_cache_usage_perc`, `vllm:gpu_prefix_cache_*` — scraped by
the router, src/vllm_router/stats/engine_stats.py:63-76). This module is the
TPU engine's equivalent: host-side bookkeeping for the device-side paged pool
(the actual pages live in one stacked jax.Array, models/llama.py
init_kv_cache). Block 0 is the reserved null page (ops/attention.py).

Prefix caching is content-addressed like vLLM's: a *full* block's identity is
the rolling hash of (parent block hash, its tokens). Blocks whose refcount
drops to zero are not returned to the free list immediately — they park in an
LRU of evictable cached blocks, so a new request with a shared prefix can
re-reference their KV without recompute. The hit/query counters back the
`prefix_cache_hit_rate` metric contract.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from dataclasses import dataclass

_ROOT_HASH = 0x9E3779B97F4A7C15


def chain_hash(parent: int, tokens: tuple[int, ...]) -> int:
    """Collision-resistant rolling block hash. Python's hash() would make
    wrong-KV collisions constructible (even adversarially, in a multi-tenant
    server); a truncated sha256 over parent||tokens removes that."""
    h = hashlib.sha256(int(parent).to_bytes(16, "little", signed=False))
    h.update(b"".join(int(t).to_bytes(8, "little", signed=True) for t in tokens))
    return int.from_bytes(h.digest()[:16], "little")


@dataclass
class CacheStats:
    queries: int = 0  # full prompt blocks looked up
    hits: int = 0  # full prompt blocks served from cache

    @property
    def hit_rate(self) -> float:
        return self.hits / self.queries if self.queries else 0.0


class KVBlockPool:
    """Host-side accounting for the device page pool of ONE engine."""

    def __init__(
        self, num_blocks: int, block_size: int, enable_prefix_caching: bool = True
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the null page)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        # block 0 reserved as the null page
        self._free: deque[int] = deque(range(1, num_blocks))
        self._ref: dict[int, int] = {}
        # content-addressing maps (full, computed blocks only)
        self._hash_to_block: dict[int, int] = {}
        self._block_to_hash: dict[int, int] = {}
        # refcount-0 cached blocks, LRU order (oldest first -> evicted first)
        self._evictable: OrderedDict[int, None] = OrderedDict()
        self.stats = CacheStats()

    # -- capacity ----------------------------------------------------------

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        """Blocks allocatable right now (free list + evictable cached)."""
        return len(self._free) + len(self._evictable)

    @property
    def usage_perc(self) -> float:
        """Fraction of pool actively referenced — the TPU analogue of
        vllm:gpu_cache_usage_perc."""
        return 1.0 - self.num_free / self.num_usable

    # -- allocation --------------------------------------------------------

    def allocate(self) -> int | None:
        if self._free:
            blk = self._free.popleft()
        elif self._evictable:
            blk, _ = self._evictable.popitem(last=False)
            h = self._block_to_hash.pop(blk)
            self._hash_to_block.pop(h, None)
        else:
            return None
        self._ref[blk] = 1
        return blk

    def free_block(self, blk: int) -> None:
        ref = self._ref.get(blk)
        if ref is None:
            raise KeyError(f"double free of block {blk}")
        if ref > 1:
            self._ref[blk] = ref - 1
            return
        del self._ref[blk]
        if blk in self._block_to_hash:
            self._evictable[blk] = None  # parked, content still addressable
        else:
            self._free.append(blk)

    # -- prefix caching ----------------------------------------------------

    def match_prefix(self, token_ids: list[int]) -> list[int]:
        """Longest run of cached full blocks matching the prompt's head.
        Acquires a reference on every matched block."""
        matched: list[int] = []
        if not self.enable_prefix_caching:
            return matched
        parent = _ROOT_HASH
        n_full = len(token_ids) // self.block_size
        for i in range(n_full):
            self.stats.queries += 1
            chunk = tuple(token_ids[i * self.block_size : (i + 1) * self.block_size])
            h = chain_hash(parent, chunk)
            blk = self._hash_to_block.get(h)
            if blk is None:
                break
            self.stats.hits += 1
            self._acquire(blk)
            matched.append(blk)
            parent = h
        return matched

    def _acquire(self, blk: int) -> None:
        if blk in self._ref:
            self._ref[blk] += 1
        else:
            self._ref[blk] = 1
            self._evictable.pop(blk, None)

    def register_full_block(
        self, blk: int, parent_hash: int, tokens: tuple[int, ...]
    ) -> int:
        """Make a freshly computed full block content-addressable. Returns the
        chain hash to use as the next block's parent."""
        h = chain_hash(parent_hash, tokens)
        if not self.enable_prefix_caching:
            return h
        if h not in self._hash_to_block:
            self._hash_to_block[h] = blk
            self._block_to_hash[blk] = h
        return h

    @staticmethod
    def root_hash() -> int:
        return _ROOT_HASH

    def reset_stats(self) -> None:
        self.stats = CacheStats()

    def clear_prefix_cache(self) -> None:
        """Drop all content-addressing state. MUST be called whenever the
        device-side pool is reinitialized (sleep/wake, weight reload): the
        hashes describe KV bytes that no longer exist, and serving a match
        against a zeroed page would silently corrupt attention."""
        if self._ref:
            raise RuntimeError(
                "cannot clear prefix cache while blocks are referenced"
            )
        self._hash_to_block.clear()
        self._block_to_hash.clear()
        for blk in self._evictable:
            self._free.append(blk)
        self._evictable.clear()
