"""Model runner: jitted, bucketed device steps over the paged pool.

This is the layer the reference never has to build (its engines are external
vLLM images). Responsibilities:

- hold params + the device KV pool, sharded over the (dp, tp) mesh;
- compile ONE prefill step per (chunk-bucket) and ONE decode step per
  (batch-bucket) — static shapes so XLA compiles a handful of programs total
  (SURVEY §7.3 hard part 1: shape bucketing vs recompilation);
- fuse forward + logits + sampling into a single jit so the only per-step
  host transfer is the sampled token ids;
- donate the KV pool into each step so updates are in-place in HBM.

Work items arrive as logical (unpadded) batches from the scheduler; padding
rows write to the reserved null page (block 0) and their samples are dropped.
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import llama
from ..ops.attention import gather_pages
from ..utils.logging import init_logger
from ..parallel import mesh as mesh_lib
from ..parallel.sharding import (
    kv_cache_spec,
    llama_param_specs,
    lora_param_specs,
)
from .compile_watch import CompileWatch, program_memory_bytes
from .config import EngineConfig
from .sampling import (
    SUPPRESS_IDS, apply_grammar_mask, greedy_argmax, sample,
    suppress_stop_tokens,
)
from .scheduler import DecodeWork, PrefillWork, ScheduleOutput, VerifyWork

logger = init_logger(__name__)

# top-N alternatives collected when a batch contains logprobs requests —
# static (one extra compiled variant per program, lazily); requests asking
# for more are rejected at the API layer
LOGPROBS_TOPN = 8


def resolve_auto_attention_backend(
    *, block_size: int, max_model_len: int, mesh_size: int,
    kv_quantized: bool, platform: str,
) -> str:
    """The 'auto' decode-attention choice as a pure predicate of the
    static engine config (testable without a device). Derived from the
    v5e sweep in ModelRunner._resolve_attention_backend: the Pallas kernel
    wins at >=32-token pages in the LONG-context regime (ctx ~4k: -7% to
    -19%); at ~1k contexts the outcome is batch-dependent (XLA wins at
    batch 16, the kernel edges batch 64/block 64 by ~6%) — the gate keys
    on max_model_len because batch varies at runtime while the program is
    compiled per config, a deliberately conservative trade. Single-device
    unquantized pools on a real TPU only for 'auto' (no GSPMD partition
    rule; Mosaic-compiled); explicit 'pallas' also supports fp8 pools."""
    if (
        block_size >= 32
        and max_model_len >= 4096
        and mesh_size == 1
        and not kv_quantized
        and platform == "tpu"
    ):
        return "pallas"
    return "xla"


def resolve_auto_prefill_backend(
    *, block_size: int, max_model_len: int, platform: str,
    heads_divisible: bool,
) -> str:
    """The 'auto' PREFILL-attention choice, independent of decode: the
    paged flash-prefill kernel (ops/paged_attention_pallas.py:
    paged_prefill_attention) streams pool pages HBM→VMEM with a resident
    query tile and never materializes the gathered (B, S, kvH, D) history
    OR the (B, T, S) mask.

    Gate: 'auto' returns XLA until the kernel's on-chip sweep lands —
    auto must only ever pick MEASURED winners (the decode gate's
    discipline), and the chip was unreachable when the kernel shipped
    (ROUND5.md hardware caveat). The expected winning regime mirrors
    decode's (the same page-DMA-size argument applies: 16-token pages
    make per-page DMAs/matmuls too small, while the XLA gather's cost
    tracks gathered bytes — paid per CHUNK in prefill, so long contexts
    should favor the kernel strongly). To enable: run
    benchmarks/sweep_attention.py --prefill on a chip, paste the table
    here, and gate like the decode predicate. Until then the explicit
    'pallas' knob is the opt-in (parity is pinned by
    tests/test_pallas_attention.py; only perf is unmeasured).
    heads_divisible is still threaded so the eventual gate composes with
    tp meshes the same way the explicit knob's checks do."""
    del block_size, max_model_len, heads_divisible  # used once measured
    del platform
    return "xla"


class StepHandle:
    """One dispatched-but-unresolved device step — the async pipeline's
    unit of in-flight work (engine/engine.py pipelined step loop).

    Holds the ON-DEVICE sampled-token matrix (and logprob arrays) so the
    engine can dispatch the NEXT step, chaining decode inputs device-side
    from `tokens`, before paying the single batched D2H transfer that
    resolve() performs. discard() is the rollback hook: the device still
    executes the step, but its results are dropped and the runner's RNG
    rewinds so the replacement dispatch draws the same step key the serial
    loop would have."""

    def __init__(self, runner, work, tokens, lp_arrays, rng_before, postproc):
        self.runner = runner
        self.work = work
        self.tokens = tokens  # device array; decode: (B_pad, window)
        self.lp_arrays = lp_arrays  # tuple of device arrays, or None
        self.rng_before = rng_before
        self._postproc = postproc
        self.logprob_rows: list | None = None
        self.sync_s = 0.0  # host time blocked in the D2H sync
        self._rows: list[list[int]] | None = None
        # verify handles: (B_pad,) device vector of each row's full-
        # acceptance bonus token — the chain source for a decode window
        # dispatched on top of this still-in-flight verify step (decode
        # handles chain from tokens[:, -1] instead; see _chain_fn)
        self.chain_vec = None
        # grammar-enabled decode handles: (B_pad,) device vector of each
        # row's automaton state AFTER the window — a chained next window
        # gathers its gr_state0 from it the same way tokens chain
        self.grammar_states = None

    def resolve(self) -> list[list[int]]:
        """Sync the step's results to the host — exactly ONE jax.device_get
        covering tokens + every logprob array — and build the per-request
        token rows. Idempotent: the transfer happens once."""
        if self._rows is None:
            t0 = time.perf_counter()
            if self.lp_arrays is not None:
                got = jax.device_get((self.tokens, *self.lp_arrays))
                mat = np.asarray(got[0])
                lp = tuple(np.asarray(x) for x in got[1:])
            else:
                mat = np.asarray(jax.device_get(self.tokens))
                lp = None
            self.sync_s = time.perf_counter() - t0
            self._rows, self.logprob_rows = self._postproc(mat, lp)
        return self._rows

    def discard(self) -> None:
        """Roll back this dispatch (speculation invalidated): rewind the
        runner RNG — valid because nothing else dispatches between a
        speculative step and its rollback decision — and drop the results."""
        self.runner._rng = self.rng_before
        self._rows = []
        self.logprob_rows = None


def _collect_logprobs(logits: jax.Array, tokens: jax.Array):
    """(chosen_lp (S,), top_lp (S, N), top_id (S, N)) from (S, V) logits."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    chosen = jnp.take_along_axis(lp, tokens[:, None].astype(jnp.int32), 1)[:, 0]
    top_lp, top_id = jax.lax.top_k(lp, LOGPROBS_TOPN)
    return chosen, top_lp, top_id.astype(jnp.int32)


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        params: Any | None = None,
        mesh: jax.sharding.Mesh | None = None,
    ):
        self.config = config
        cfg = config.model
        pp = config.parallel.pipeline_parallel_size
        if pp > 1:
            # friendly errors beat JAX's deep 'dimension not divisible'
            if cfg.num_layers % pp:
                raise ValueError(
                    f"num_layers={cfg.num_layers} must be divisible by "
                    f"pipeline_parallel_size={pp} (layer axis shards over "
                    "pp stages)"
                )
            if config.cache.num_blocks and config.cache.num_blocks % pp:
                raise ValueError(
                    f"num_blocks={config.cache.num_blocks} must be divisible "
                    f"by pipeline_parallel_size={pp} (the pool's block axis "
                    "shards over pp stages); round it or leave num_blocks "
                    "unset to derive from HBM"
                )
        self.mesh = mesh or mesh_lib.make_mesh(
            config.parallel.tensor_parallel_size,
            config.parallel.data_parallel_size,
            config.parallel.pipeline_parallel_size,
            sequence_parallel_size=config.parallel.sequence_parallel_size,
            expert_parallel_size=config.parallel.expert_parallel_size,
        )
        self.max_blocks = config.cache.max_blocks_per_seq(cfg.max_model_len)

        param_shardings = self._param_shardings()
        if params is None and cfg.checkpoint:
            from ..models.loader import load_checkpoint_params

            params = load_checkpoint_params(cfg)
            if cfg.quantization:
                # host-side (numpy): the device never holds the bf16 tree
                from ..models.quantization import quantize_params

                params = quantize_params(cfg, params)
        self._random_weights = params is None
        if params is None:
            logger.info(
                "initializing random weights for %s%s", cfg.model,
                f" ({cfg.quantization} weight-only)" if cfg.quantization
                else "",
            )
            # one compiled program materializing the whole tree directly into
            # its sharded HBM layout (eager per-weight RNG dispatches are
            # painfully slow through remote-device tunnels)
            self.params = self._init_device_params(param_shardings)
        else:
            self.params = jax.tree.map(jax.device_put, params, param_shardings)
        kv_sharding = NamedSharding(self.mesh, kv_cache_spec())
        self._kv_dtype = config.cache.resolved_kv_dtype(cfg.dtype)
        self.kv_caches = jax.jit(
            lambda: llama.init_kv_cache(
                cfg, config.cache.num_blocks, config.cache.block_size,
                dtype=self._kv_dtype,
            ),
            out_shardings=kv_sharding,
        )()
        self._use_lora = config.lora.max_loras > 0
        if self._use_lora:
            lora_sh = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                lora_param_specs(cfg, config.lora),
            )
            self.lora_params = jax.jit(
                lambda: llama.init_lora_params(cfg, config.lora),
                out_shardings=lora_sh,
            )()
            self._lora_shardings = lora_sh
        else:
            self.lora_params = None
        self._rng = jax.random.PRNGKey(config.seed ^ 0x5EED)
        self._rep = NamedSharding(self.mesh, P())
        # dp shards every batch-dim input across the dp mesh axis: each dp
        # group computes only its rows (VERDICT r1 weak #4 — dp used to be
        # pure replication). The KV pool stays dp-replicated — block ids are
        # global, and the per-step cross-dp cost is only the all-gather of
        # the new K/V rows GSPMD inserts for the pool scatter.
        self._dp = config.parallel.data_parallel_size
        self._batch1 = NamedSharding(self.mesh, P(mesh_lib.DP_AXIS))
        self._batch2 = NamedSharding(self.mesh, P(mesh_lib.DP_AXIS, None))
        if self._dp > 1:
            if self._dp & (self._dp - 1):
                # _batch_bucket pads batches to max(dp, pow2) — only a pow2
                # dp always divides that evenly
                raise ValueError(
                    f"data_parallel_size={self._dp} must be a power of two"
                )
            bad = [
                b for b in config.scheduler.decode_buckets if b % self._dp
            ]
            if bad:
                raise ValueError(
                    f"decode_buckets {bad} not divisible by dp={self._dp}"
                )
        # sp shards the PREFILL chunk's sequence axis over the ring
        # (parallel/ring_attention.py); decode (T=1) has no sequence axis to
        # shard, so sp devices replicate decode work — size sp for prefill-
        # heavy / long-context serving (disaggregated prefill-role engines)
        self._sp = config.parallel.sequence_parallel_size
        self._seq2 = NamedSharding(
            self.mesh, P(mesh_lib.DP_AXIS, mesh_lib.SP_AXIS)
        )
        if self._sp > 1:
            bad_t = [
                t for t in config.scheduler.prefill_buckets if t % self._sp
            ]
            if bad_t:
                raise ValueError(
                    f"prefill_buckets {bad_t} not divisible by "
                    f"sequence_parallel_size={self._sp} (the chunk axis "
                    "shards evenly over the sp ring)"
                )
        if config.parallel.expert_parallel_size > 1 and not cfg.num_experts:
            raise ValueError(
                f"expert_parallel_size={config.parallel.expert_parallel_size} "
                f"requires an MoE model, but {cfg.model} has no experts — "
                "the ep axis would only replicate dense compute"
            )
        self._attention_backend = self._resolve_attention_backend()
        self._prefill_backend = self._resolve_prefill_backend()
        self._hoist_budget = self._compute_hoist_budget()
        self._step_fn = (
            self._build_sp_step_fn() if self._sp > 1 else self._build_step_fn()
        )
        self._decode_window_fn = self._build_decode_window_fn()
        self._verify_fn = (
            self._build_verify_fn()
            if config.scheduler.num_speculative_tokens > 0
            else None
        )
        # per-execute logprob rows (parallel to the returned token rows)
        # when the dispatched batch requested them; None otherwise. Read by
        # LLMEngine.step right after execute().
        self.last_logprobs: list | None = None
        # host time the last execute() spent blocked in its D2H sync (the
        # engine folds this into the timing decomposition's sync_s)
        self.last_sync_s = 0.0
        # async pipeline: splice a chained row's input token from the
        # previous step's device-resident output matrix (last window
        # column), falling back to the host-provided token where
        # chain_idx < 0 — the D2H→H2D round trip the pipeline removes
        self._chain_fn = jax.jit(
            lambda prev_toks, host_toks, idx: jnp.where(
                idx >= 0,
                jnp.take(
                    prev_toks[:, -1],
                    jnp.clip(idx, 0, prev_toks.shape[0] - 1),
                ),
                host_toks,
            ),
            out_shardings=NamedSharding(self.mesh, P(mesh_lib.DP_AXIS)),
        )
        # chain variant for a previous VERIFY step: its per-row next-input
        # token is position-dependent (each row's last real fed column), so
        # the handle carries a precomputed (B_pad,) vector instead of a
        # matrix column
        self._chain_vec_fn = jax.jit(
            lambda prev_vec, host_toks, idx: jnp.where(
                idx >= 0,
                jnp.take(prev_vec, jnp.clip(idx, 0, prev_vec.shape[0] - 1)),
                host_toks,
            ),
            out_shardings=NamedSharding(self.mesh, P(mesh_lib.DP_AXIS)),
        )
        # verify-on-verify chaining (docs/36): a chained verify row's FIRST
        # fed token is the in-flight verify's bonus token (chain_vec) —
        # spliced into column 0 of the fed-token matrix device-side, since
        # its value exists nowhere on the host yet
        self._chain_verify_fn = jax.jit(
            lambda prev_vec, toks, idx: toks.at[:, 0].set(
                jnp.where(
                    idx >= 0,
                    jnp.take(
                        prev_vec, jnp.clip(idx, 0, prev_vec.shape[0] - 1)
                    ),
                    toks[:, 0],
                )
            ),
            out_shardings=NamedSharding(self.mesh, P(mesh_lib.DP_AXIS, None)),
        )
        self._zero_stop_arrays: dict[int, tuple] = {}
        # structured output (docs/41-structured-output.md): device-resident
        # automaton tables keyed by (grammar uids, pads) — a steady
        # constrained batch re-dispatches with zero H2D table traffic —
        # plus cached all-ones prefill masks (the identity rows a grammar-
        # enabled program feeds its unconstrained batches)
        self._grammar_tables_cache: dict[tuple, tuple] = {}
        self._ones_mask_cache: dict[int, Any] = {}
        self._gr_eos_dev: Any | None = None
        self._sleeping_params_host: Any | None = None
        self._sleeping_lora_host: Any | None = None
        self._upload_block_fn = None
        self._fetch_block_fn = None
        self._embed_fn = None
        # -- compile-stall avoidance (the measured live-serving collapse
        # mode: a first-seen (rows × chunk × width) program key froze
        # serving 30-60s mid-traffic). The runner tracks which program keys
        # are compiled; a miss PADS UP to an already-compiled dominating
        # program (more padding = identical results, bounded extra compute)
        # and hands the exact key to a background thread that AOT-compiles
        # it (.lower().compile() — populates jax's in-process+persistent
        # caches without executing), so the NEXT hit runs specialized.
        self._compiled_keys: set[tuple] = set()
        self._aot_exec: dict[tuple, Any] = {}
        self._bg_inflight: set[tuple] = set()
        self._bg_lock = threading.Lock()
        self._bg_executor: ThreadPoolExecutor | None = None
        self._bg_stop = threading.Event()  # shutdown() -> running job bails
        self.compile_fallbacks = 0  # profiling: pad-up substitutions taken
        self.bg_compiles = 0  # profiling: programs compiled off the hot path
        # warmup disables this so every wave compiles its EXACT program
        self.fallback_enabled = True
        # thread-liveness heartbeat (docs/37-flight-recorder.md,
        # flightrec.ThreadRegistry "bg_compile"; the engine wires it):
        # busy only while a background compile actually runs — a beat
        # older than its generous threshold while busy is the
        # "XLA compiles forever" wedge the watchdog names
        self.heartbeat = None
        # when set (AsyncEngine wires it), background compiles WAIT for the
        # engine to go idle: on remote-device links the compile service
        # contends with dispatch, so compiling during traffic steals the
        # serving time the background thread exists to protect (measured:
        # ~10x prefill dispatch inflation with compiles in flight)
        self.idle_check = None  # Callable[[], bool] | None
        # XLA compile telemetry (docs/42-compile-telemetry.md): the engine
        # replaces this with its shared CompileWatch; the disabled default
        # keeps a standalone runner importable at zero overhead. The draft
        # runner shares the target's watch with role="draft".
        self.compile_watch = CompileWatch(enabled=False)
        self.compile_role = "target"
        # verify programs have no pad-up fallback lattice (t_pad is the
        # pow2 of the fed width) — tracked separately for telemetry only
        self._verify_keys: set[tuple] = set()

    def _resolve_attention_backend(self) -> str:
        """'auto' → the measured winner for the pool's block size.

        Swept on a v5e chip (benchmarks/sweep_attention.py, llama-1b decode
        head shape, 64-iteration on-device loops, ms/iter):

            batch ctx   block   pallas   xla     winner
            16    1024  16      1.93     1.63    xla
            16    1024  32      1.73     1.58    xla
            16    1024  64      1.63     1.57    xla (±4%)
            16    4096  16      3.40     2.72    xla
            16    4096  32      2.71     2.93    pallas
            16    4096  64      2.48     2.50    pallas (±1%)
            64    1024  16      3.48     3.24    xla
            64    1024  64      2.57     2.73    pallas
            64    4096  64      4.68     5.80    pallas (-19%)

        At 16-token pages the kernel's per-page pipeline (16 KB DMAs,
        16-token matmuls) loses to XLA's bulk gather — XLA is 'auto' there
        (the shipped default config). At 32/64-token pages the winner
        flips with context: XLA still edges ~1k contexts, the kernel wins
        the 4k rows — so 'auto' requires BOTH block_size >= 32 and a
        long-context engine (max_model_len >= 4096), single device,
        unquantized (resolve_auto_attention_backend — the pure predicate
        tests pin). The kernel also never materializes the O(B×S) gather
        scratch that OOMs large models (bench_northstar.py's llama-3b
        finding). Explicit 'pallas' stays single-device-only (no GSPMD
        partition rule for pallas_call; wrap in shard_map before enabling
        under tp>1); CPU tests pin numerics via interpret mode."""
        backend = self.config.attention_backend
        if self.config.model.any_sliding:
            # sliding-window models (Mistral-v0.1, Gemma-2 class): the
            # Pallas kernels have no window masking yet — XLA only
            if backend in ("pallas", "pallas_interpret"):
                raise ValueError(
                    "attention_backend='pallas' does not support "
                    "sliding-window models; use 'xla'"
                )
            return "xla"
        if backend == "auto":
            return resolve_auto_attention_backend(
                block_size=self.config.cache.block_size,
                max_model_len=self.config.model.max_model_len,
                mesh_size=self.mesh.size,
                kv_quantized=self._kv_dtype != self.config.model.dtype,
                platform=jax.devices()[0].platform,
            )
        if backend not in ("xla", "pallas", "pallas_interpret"):
            raise ValueError(
                f"unknown attention_backend {backend!r}; expected one of "
                "'auto', 'xla', 'pallas', 'pallas_interpret'"
            )
        if backend.startswith("pallas") and self.mesh.size > 1:
            # shard_map places kernel instances per device over (dp, tp) —
            # the axes decode attention parallelizes over with no
            # collective. pp/sp/ep shard things the kernel can't split
            # (the pool's block axis, the sequence axis, experts).
            par = self.config.parallel
            if (
                par.pipeline_parallel_size > 1
                or par.sequence_parallel_size > 1
                or par.expert_parallel_size > 1
            ):
                raise ValueError(
                    "attention_backend='pallas' supports dp/tp meshes only "
                    "(pp/sp/ep shard axes the decode kernel cannot split)"
                )
            tp = par.tensor_parallel_size
            if self.config.model.num_heads % tp or (
                self.config.model.num_kv_heads % tp
            ):
                raise ValueError(
                    f"attention_backend='pallas' under tp={tp} needs "
                    "num_heads and num_kv_heads divisible by tp"
                )
        # quantized (fp8) pools are supported: the kernel casts pages to
        # f32 as they stream into VMEM (Mosaic handles f8e4m3 loads on
        # v5e), same upconvert the XLA path does — pinned by
        # tests/test_pallas_attention.py::test_pallas_fp8_pool_numerics
        return backend

    def _resolve_prefill_backend(self) -> str:
        """Prefill attention backend, resolved independently of decode
        (resolve_auto_prefill_backend has the gate rationale). The sp path
        (ring attention) ignores this — it has its own sharded prefill."""
        par = self.config.parallel
        tp = par.tensor_parallel_size
        heads_ok = (
            self.config.model.num_heads % tp == 0
            and self.config.model.num_kv_heads % tp == 0
            and par.sequence_parallel_size == 1
            and par.pipeline_parallel_size == 1
            and par.expert_parallel_size == 1
        )
        backend = self.config.prefill_attention_backend
        if self.config.model.any_sliding:
            if backend in ("pallas", "pallas_interpret"):
                raise ValueError(
                    "prefill_attention_backend='pallas' does not support "
                    "sliding-window models; use 'xla'"
                )
            return "xla"
        if backend == "auto":
            return resolve_auto_prefill_backend(
                block_size=self.config.cache.block_size,
                max_model_len=self.config.model.max_model_len,
                platform=jax.devices()[0].platform,
                heads_divisible=heads_ok,
            )
        if backend not in ("xla", "pallas", "pallas_interpret"):
            raise ValueError(
                f"unknown prefill_attention_backend {backend!r}; expected "
                "one of 'auto', 'xla', 'pallas', 'pallas_interpret'"
            )
        if backend.startswith("pallas") and self.mesh.size > 1 and not heads_ok:
            raise ValueError(
                f"prefill_attention_backend='pallas' under tp={tp} needs "
                "num_heads and num_kv_heads divisible by tp, and a dp/tp "
                "mesh (pp/sp/ep shard axes the kernel cannot split)"
            )
        return backend

    def _compute_hoist_budget(self) -> int:
        """Per-device HBM headroom (bytes) available for hoisting the decode
        window's loop-invariant history gather out of the loop (one
        contiguous per-layer K/V copy per window instead of a fresh gather
        per iteration — the measured decode bottleneck; see
        ops/attention.py:attention_with_hist). Headroom = utilization-capped
        HBM − pool − weights − reserve; each compiled window program compares
        its own static (batch, context) hoist footprint against this and
        falls back to the per-iteration gather when it doesn't fit. The cap
        matters: memory the operator withheld via hbm_utilization (co-located
        workloads) must not be absorbed by hoisted copies."""
        from .memory import headroom_budget, kv_block_bytes

        par = self.config.parallel
        pool = self.config.cache.num_blocks * kv_block_bytes(
            self.config.model, self.config.cache.block_size,
            par.tensor_parallel_size, par.pipeline_parallel_size,
            kv_dtype=self._kv_dtype,
        )
        return max(
            0,
            headroom_budget(self.config.model, self.config.cache, par) - pool,
        )

    def _hoist_bytes(self, batch: int, s_ctx: int) -> int:
        """Per-device bytes of hoisted contiguous history for one window
        program: all layers' (B, S, kvH, D) K+V, batch sharded over dp and
        kv heads over tp. Expressed via memory.kv_block_bytes so the hoist
        budget can never diverge from the pool accounting it is compared
        against."""
        from .memory import kv_block_bytes

        par = self.config.parallel
        block_size = self.config.cache.block_size
        b_local = max(1, batch // self._dp)
        return (
            b_local
            * (s_ctx // block_size)
            * kv_block_bytes(
                self.config.model, block_size,
                par.tensor_parallel_size, par.pipeline_parallel_size,
                kv_dtype=self._kv_dtype,
            )
        )

    # -- compiled step -----------------------------------------------------

    def _build_step_fn(self):
        cfg = self.config.model

        @functools.partial(
            jax.jit,
            donate_argnames=("kv_caches",),
            static_argnames=(
                "want_logprobs", "want_min_tokens", "want_grammar"
            ),
        )
        def step_fn(
            params,
            lora_params,  # stacked adapter tree, or None when LoRA disabled
            kv_caches,
            token_ids,  # (B, T)
            positions,  # (B, T)
            block_tables,  # (B, max_blocks)
            slot_mapping,  # (1,) placeholder — only the sp path row-scatters
            context_lens,  # (B,)
            chunk_lens,  # (B,) real chunk tokens this step
            write_ids,  # (B, NBW) pool blocks of the chunk's written span
            start_off,  # (B,) chunk's first-token offset in its first block
            lora_idx,  # (B,) adapter slot per row (None when disabled)
            sample_rows,  # (num_samples,) row index into (B*T) flat hidden
            temperature,  # (num_samples,)
            top_p,  # (num_samples,)
            top_k,  # (num_samples,)
            rng,
            seeds,  # (num_samples,) int32
            has_seed,  # (num_samples,) bool
            counts,  # (num_samples,) int32 output tokens so far
            min_toks,  # (num_samples,) min_tokens per row
            stop_ids,  # (num_samples, SUPPRESS_IDS) eos/stop ids, -1 pad
            grammar_mask=None,  # (num_samples, V) bool — constrained rows'
            #   allowed tokens, all-True for unconstrained rows (mask is
            #   DATA; docs/41-structured-output.md)
            want_logprobs=False,  # static: also return chosen/top-N logprobs
            want_min_tokens=False,  # static: suppression costs a full-logits
            #   copy per dispatch, so it only compiles in when a row needs it
            want_grammar=False,  # static: grammar masking compiles in only
            #   when a batch row is constrained
        ):
            hidden, kv_caches = llama.forward(
                cfg, params, token_ids, positions, kv_caches,
                block_tables, slot_mapping, context_lens,
                lora=lora_params, lora_idx=lora_idx,
                write_blocks={
                    "ids": write_ids,
                    "start_off": start_off,
                    "chunk_lens": chunk_lens,
                },
                backend=self._prefill_backend,
                mesh=self.mesh,
            )
            flat = hidden.reshape(-1, hidden.shape[-1])
            picked = flat[sample_rows]  # (num_samples, h)
            logits = llama.compute_logits(cfg, params, picked)
            if want_grammar:
                # masked logits flow into logprobs too: the reported
                # distribution is the constrained one actually sampled from
                logits = apply_grammar_mask(logits, grammar_mask)
            if want_min_tokens:
                logits = suppress_stop_tokens(
                    logits, counts, min_toks, stop_ids
                )
            tokens = sample(
                logits, temperature, top_p, top_k, rng, seeds, has_seed, counts
            )
            if want_logprobs:
                return kv_caches, tokens, _collect_logprobs(logits, tokens)
            return kv_caches, tokens

        return step_fn

    def _build_sp_step_fn(self):
        """Prefill step with the chunk's sequence axis sharded over the sp
        mesh axis — ring attention seeded with the pooled history block
        (models/llama.py:forward_sp_prefill). Same signature as the paged
        step so the host-side batching code is identical."""
        cfg = self.config.model
        mesh = self.mesh

        @functools.partial(
            jax.jit,
            donate_argnames=("kv_caches",),
            static_argnames=(
                "want_logprobs", "want_min_tokens", "want_grammar"
            ),
        )
        def sp_step_fn(
            params,
            lora_params,
            kv_caches,
            token_ids,  # (B, T) — T sharded over sp
            positions,  # (B, T)
            block_tables,  # (B, max_blocks)
            slot_mapping,  # (B*T,)
            context_lens,  # (B,) resident AFTER this chunk
            chunk_lens,  # (B,) real chunk tokens this step
            write_ids,  # unused: the sp path row-scatters (sharded over sp)
            start_off,  # unused
            lora_idx,
            sample_rows,
            temperature,
            top_p,
            top_k,
            rng,
            seeds,
            has_seed,
            counts,
            min_toks,
            stop_ids,
            grammar_mask=None,
            want_logprobs=False,
            want_min_tokens=False,
            want_grammar=False,
        ):
            del write_ids, start_off
            hist_lens = context_lens - chunk_lens
            hidden, kv_caches = llama.forward_sp_prefill(
                cfg, params, token_ids, positions, kv_caches, block_tables,
                slot_mapping, chunk_lens, hist_lens, mesh,
                lora=lora_params, lora_idx=lora_idx,
            )
            flat = hidden.reshape(-1, hidden.shape[-1])
            picked = flat[sample_rows]
            logits = llama.compute_logits(cfg, params, picked)
            if want_grammar:
                logits = apply_grammar_mask(logits, grammar_mask)
            if want_min_tokens:
                logits = suppress_stop_tokens(
                    logits, counts, min_toks, stop_ids
                )
            tokens = sample(
                logits, temperature, top_p, top_k, rng, seeds, has_seed, counts
            )
            if want_logprobs:
                return kv_caches, tokens, _collect_logprobs(logits, tokens)
            return kv_caches, tokens

        return sp_step_fn

    def _build_decode_window_fn(self):
        """K decode iterations fused into one dispatch: a lax.fori_loop feeds
        each iteration's sampled tokens into the next ON DEVICE and returns
        the (B, K) token matrix in a single fetch. Host↔device round-trip
        latency — the dominant per-step cost, especially through
        remote-device tunnels — amortizes over B*K tokens instead of B.

        The KV pool is deliberately NOT a loop carry: each iteration writes
        its K/V into a small (L, 2, W, B, kvH, D) staging buffer and attends
        over [pooled history + staged window]; the pool is scattered into
        once, after the loop. Carrying the pool ping-pongs it in the while
        body — two extra full-pool buffers of compile-time temp (measured
        2.0 GiB pool → 4.28 GiB temp), which is what used to cap pool sizes
        far below HBM."""
        cfg = self.config.model
        block_size = self.config.cache.block_size

        @functools.partial(
            jax.jit,
            static_argnames=(
                "window", "want_logprobs", "want_min_tokens", "want_grammar"
            ),
            donate_argnames=("kv_caches",),
        )
        def decode_window_fn(
            params,
            lora_params,  # stacked adapter tree, or None when LoRA disabled
            kv_caches,
            first_tokens,  # (B,) input token per request
            positions0,  # (B,) first decode position per request
            block_tables,  # (B, max_blocks) covering the whole window
            lora_idx,  # (B,) adapter slot per row (None when disabled)
            temperature,  # (B,)
            top_p,  # (B,)
            top_k,  # (B,)
            base_key,
            seeds,  # (B,) uint32
            has_seed,  # (B,) bool
            counts0,  # (B,) output tokens generated before this window
            min_toks,  # (B,) min_tokens per row
            stop_ids,  # (B, SUPPRESS_IDS) eos/stop ids, -1 pad
            # structured output (docs/41-structured-output.md): the token-
            # class automaton runs ON DEVICE inside the window loop — the
            # precomputed tables arrive as DATA padded to (G, S, C) buckets,
            # so every iteration masks AND advances without a host hop and
            # constrained rows keep full window throughput
            gr_token_class=None,  # (G, V) int32 vocab token -> class
            gr_class_dest=None,  # (G, S, C) int32 dest state, -1 = reject
            gr_accepting=None,  # (G, S) bool — EOS allowed here
            gr_idx=None,  # (B,) int32 row -> grammar index, -1 unconstrained
            gr_state0=None,  # (B,) int32 automaton state entering the window
            gr_eos=None,  # (1,) int32 EOS token id
            window: int = 1,
            want_logprobs: bool = False,
            want_min_tokens: bool = False,
            want_grammar: bool = False,
        ):
            b = first_tokens.shape[0]
            out = jnp.zeros((b, window), jnp.int32)
            lp_out = jnp.zeros((b, window), jnp.float32)
            top_lp_out = jnp.zeros((b, window, LOGPROBS_TOPN), jnp.float32)
            top_id_out = jnp.zeros((b, window, LOGPROBS_TOPN), jnp.int32)
            staged = llama.init_staged_kv(cfg, window, b)
            # hoist the loop-invariant history gather out of the window loop
            # when this program's contiguous copy fits HBM headroom (static
            # per compiled (batch, nb, window) program — no runtime branch)
            s_ctx = block_tables.shape[1] * self.config.cache.block_size
            hoist = (
                self._attention_backend == "xla"
                and self._hoist_bytes(b, s_ctx) <= self._hoist_budget
            )
            hists = (
                tuple(
                    gather_pages(kv_caches[i], block_tables)
                    for i in range(cfg.num_layers)
                )
                if hoist
                else None
            )

            if want_grammar:
                has_gr = gr_idx >= 0  # (B,)
                g = jnp.clip(gr_idx, 0, gr_token_class.shape[0] - 1)
                tclass = gr_token_class[g]  # (B, V)
                # dead sink: the LAST padded state row is all -1 by
                # construction (_grammar_device_tables pads S to a bucket
                # strictly above any real state count), so a rejected
                # transition parks there and stays there
                dead = gr_class_dest.shape[1] - 1

            def body(k, carry):
                if want_grammar:
                    (staged, cur, out, lp_out, top_lp_out, top_id_out,
                     gstate) = carry
                else:
                    staged, cur, out, lp_out, top_lp_out, top_id_out = carry
                # pool history for row r is positions < positions0[r]; the
                # window's own tokens live in `staged` until the final commit
                hidden, staged = llama.decode_window_step(
                    cfg, params, cur, positions0 + k, kv_caches,
                    block_tables, staged, k, positions0,
                    backend=self._attention_backend,
                    lora=lora_params, lora_idx=lora_idx, hists=hists,
                    mesh=self.mesh,
                )
                logits = llama.compute_logits(cfg, params, hidden)
                if want_grammar:
                    # the automaton advances ON DEVICE: mask from the current
                    # state's class row, sample, then step the state — so a
                    # constrained row accepts the whole window like any other
                    # row instead of bailing after one host-masked token
                    dest_c = gr_class_dest[g, gstate]  # (B, C)
                    allowed = jnp.take_along_axis(
                        dest_c >= 0, tclass, axis=1
                    )  # (B, V)
                    # EOS is not a grammar byte: allowed exactly in
                    # accepting states (empty-content tokens hold dest -1
                    # everywhere, so BOS/PAD stay rejected)
                    allowed = allowed.at[:, gr_eos[0]].set(
                        gr_accepting[g, gstate]
                    )
                    allowed = allowed | ~has_gr[:, None]
                    logits = apply_grammar_mask(logits, allowed)
                if want_min_tokens:
                    logits = suppress_stop_tokens(
                        logits, counts0 + k, min_toks, stop_ids
                    )
                toks = sample(
                    logits, temperature, top_p, top_k,
                    jax.random.fold_in(base_key, k),
                    seeds, has_seed, counts0 + k,
                )
                if want_logprobs:
                    chosen, top_lp, top_id = _collect_logprobs(logits, toks)
                    lp_out = lp_out.at[:, k].set(chosen)
                    top_lp_out = top_lp_out.at[:, k].set(top_lp)
                    top_id_out = top_id_out.at[:, k].set(top_id)
                if want_grammar:
                    tcls = jnp.take_along_axis(tclass, toks[:, None], axis=1)
                    nxt = jnp.take_along_axis(dest_c, tcls, axis=1)[:, 0]
                    gstate = jnp.where(
                        has_gr, jnp.where(nxt >= 0, nxt, dead), gstate
                    )
                    return (
                        staged, toks, out.at[:, k].set(toks),
                        lp_out, top_lp_out, top_id_out, gstate,
                    )
                return (
                    staged, toks, out.at[:, k].set(toks),
                    lp_out, top_lp_out, top_id_out,
                )

            if want_grammar:
                (staged, _, out, lp_out, top_lp_out, top_id_out,
                 gstates) = jax.lax.fori_loop(
                    0, window, body,
                    (staged, first_tokens, out, lp_out, top_lp_out,
                     top_id_out, gr_state0),
                )
            else:
                gstates = None
                (staged, _, out, lp_out,
                 top_lp_out, top_id_out) = jax.lax.fori_loop(
                    0, window, body,
                    (staged, first_tokens, out, lp_out, top_lp_out,
                     top_id_out),
                )
            # commit the window's KV to the pool: slots for row r, step k are
            # position positions0[r] + k via the row's block table
            pos = positions0[:, None] + jnp.arange(window, dtype=jnp.int32)[None, :]
            blk = jnp.take_along_axis(block_tables, pos // block_size, axis=1)
            slots = (blk * block_size + pos % block_size).reshape(-1)
            kv_caches = llama.commit_staged_kv(kv_caches, staged, slots)
            # grammar programs also return the final per-row automaton
            # state — a chained next window gathers its gr_state0 from it
            # on device (same pattern as chain_vec for tokens)
            ret = (kv_caches, out)
            if want_logprobs:
                ret = ret + ((lp_out, top_lp_out, top_id_out),)
            if want_grammar:
                ret = ret + (gstates,)
            return ret

        return decode_window_fn

    def _build_verify_fn(self):
        """Speculative-verification program (engine/spec_decode.py): a
        chunked-prefill-shaped forward over [current token + proposals] with
        GREEDY argmax at EVERY position (sampling.greedy_argmax — the same
        pick the decode window's temperature-0 branch makes) — m[j]
        confirms or replaces the proposal for position j+1, so one dispatch
        yields 1..k+1 tokens per row. Same paged attention + blockwise KV
        commit as prefill. Also returns each row's LAST usable prediction
        (the full-acceptance bonus token) as a (B,) vector so the pipelined
        loop can chain the next decode window's input from this still-in-
        flight step without a host round trip."""
        cfg = self.config.model

        @functools.partial(
            jax.jit,
            static_argnames=("want_grammar",),
            donate_argnames=("kv_caches",),
        )
        def verify_fn(
            params,
            lora_params,
            kv_caches,
            token_ids,  # (B, T) fed tokens: [cur, p0..pk-1], padded
            positions,  # (B, T)
            block_tables,  # (B, nb)
            context_lens,  # (B,) resident after this step
            chunk_lens,  # (B,) real fed tokens per row
            write_ids,  # (B, NBW)
            start_off,  # (B,)
            lora_idx,
            # structured output: per-position admissibility, host-built from
            # the host-known proposals (TokenGrammar.verify_masks) — "the
            # verifier masks, the proposer need not": a grammar-violating
            # draft token just loses the argmax match and gets cut by the
            # normal acceptance scan, riding PR 14's rollback machinery
            grammar_mask=None,  # (B, T, V) bool
            want_grammar: bool = False,
        ):
            hidden, kv_caches = llama.forward(
                cfg, params, token_ids, positions, kv_caches,
                block_tables, jnp.zeros((1,), jnp.int32), context_lens,
                lora=lora_params, lora_idx=lora_idx,
                write_blocks={
                    "ids": write_ids,
                    "start_off": start_off,
                    "chunk_lens": chunk_lens,
                },
            )
            logits = llama.compute_logits(
                cfg, params, hidden.reshape(-1, hidden.shape[-1])
            )
            if want_grammar:
                logits = apply_grammar_mask(
                    logits, grammar_mask.reshape(-1, grammar_mask.shape[-1])
                )
            toks = greedy_argmax(logits)
            mat = toks.reshape(hidden.shape[0], hidden.shape[1])
            # row i's bonus token under full acceptance sits at its last
            # real fed column — the chain source for a dispatched-on-top
            # decode window
            nxt = jnp.take_along_axis(
                mat, jnp.maximum(chunk_lens - 1, 0)[:, None], axis=1
            )[:, 0]
            return kv_caches, mat, nxt

        return verify_fn

    def _dispatch_verify(
        self, work: VerifyWork, prev: StepHandle | None = None
    ) -> StepHandle:
        """Dispatch one speculative-verify step WITHOUT syncing results —
        the pipelined loop's verify entry point (the serial path resolves
        the returned handle immediately). The handle carries `chain_vec`,
        the on-device per-row bonus-token vector a chained next step
        (decode window OR another verify) gathers its input from. Rows
        whose work.chain_rows entry is >= 0 take their FIRST fed token
        from `prev`'s chain_vec — the still-unresolved bonus token of the
        in-flight verify they stack on."""
        # logprobs requests are routed away from the verify path
        # (scheduler._schedule_decode_or_verify)
        self.last_logprobs = None
        sched = self.config.scheduler
        b = len(work.requests)
        b_pad = sched.bucket_for(b, sched.decode_buckets)
        t = max(len(row) for row in work.token_ids)
        t_pad = max(2, self._pow2(t))  # tiny chunks: k+1 <= 8 typically

        bs = self.config.cache.block_size
        nbw = (t_pad - 1) // bs + 2
        token_ids = np.zeros((b_pad, t_pad), np.int32)
        positions = np.zeros((b_pad, t_pad), np.int32)
        context_lens = np.zeros(b_pad, np.int32)
        chunk_lens = np.zeros(b_pad, np.int32)
        write_ids = np.zeros((b_pad, nbw), np.int32)
        start_off = np.zeros(b_pad, np.int32)
        lora_idx = np.zeros(b_pad, np.int32)
        for i, req in enumerate(work.requests):
            row = work.token_ids[i]
            token_ids[i, : len(row)] = row
            positions[i, : len(row)] = work.positions[i]
            context_lens[i] = work.context_lens[i]
            chunk_lens[i] = len(row)
            hist = work.context_lens[i] - len(row)
            first_blk = hist // bs
            n_span = (work.context_lens[i] - 1) // bs - first_blk + 1
            write_ids[i, :n_span] = req.block_table[first_blk : first_blk + n_span]
            start_off[i] = hist % bs
            lora_idx[i] = req.lora_index
        block_tables = self._block_table_array(
            [r.block_table for r in work.requests], pad_to=b_pad
        )
        # structured output: per-position masks are host-buildable because a
        # verify row's fed tokens are host-known (the scheduler never chains
        # a constrained row's verify input from an in-flight step). Position
        # j's logits predict the token after fed[0..j], and fed[0] is the
        # last ACCEPTED token — already consumed by the host cursor — so
        # verify_masks(state, proposals, width) lines up exactly.
        want_gr = any(
            r.sampling.grammar is not None and r.grammar is not None
            for r in work.requests
        )
        grammar_mask = None
        if want_gr:
            v = self.config.model.vocab_size
            grammar_mask = np.ones((b_pad, t_pad, v), dtype=bool)
            for i, req in enumerate(work.requests):
                if req.sampling.grammar is None or req.grammar is None:
                    continue
                req.grammar.sync(req.output_token_ids)
                if req.grammar.state < 0:
                    continue  # dead cursor (can't be live) — unconstrained
                grammar_mask[i, : len(work.token_ids[i])] = (
                    req.sampling.grammar.verify_masks(
                        req.grammar.state,
                        work.token_ids[i][1:],
                        len(work.token_ids[i]),
                    )
                )
        if self._sleeping_params_host is not None:
            raise RuntimeError("engine is sleeping; wake it before running")
        # verify programs have no pad-up lattice: the synthetic key exists
        # so compile telemetry covers this dispatch path too
        vkey = ("verify", b_pad, t_pad, nbw, want_gr)
        new_program = vkey not in self._verify_keys
        self.compile_watch.record_dispatch(
            vkey, not new_program, role=self.compile_role
        )
        t0 = time.perf_counter()
        # verify draws no RNG (pure argmax): rng_before == rng after, so a
        # discard()'s rewind is a no-op — recorded anyway for uniformity
        rng_before = self._rng
        toks_dev = self._put(token_ids, self._batch2)
        if work.chain_rows and any(c >= 0 for c in work.chain_rows):
            if prev is None or prev.chain_vec is None:
                raise RuntimeError(
                    "chained verify rows need an in-flight verify handle "
                    "(chain_vec) to splice their first fed token from"
                )
            idx = np.full(b_pad, -1, np.int32)
            idx[: len(work.chain_rows)] = work.chain_rows
            toks_dev = self._chain_verify_fn(
                prev.chain_vec, toks_dev, self._put(idx, self._batch1)
            )
        self.kv_caches, toks, nxt = self._verify_fn(
            self.params,
            self.lora_params,
            self.kv_caches,
            toks_dev,
            self._put(positions, self._batch2),
            self._put(block_tables, self._batch2),
            self._put(context_lens, self._batch1),
            self._put(chunk_lens, self._batch1),
            self._put(write_ids, self._batch2),
            self._put(start_off, self._batch1),
            self._put(lora_idx, self._batch1) if self._use_lora else None,
            grammar_mask=(
                self._put(grammar_mask, self._batch1) if want_gr else None
            ),
            want_grammar=want_gr,
        )
        if new_program:
            self._verify_keys.add(vkey)
            self._watch_sync_compile(
                "verify", vkey, time.perf_counter() - t0, work.requests
            )
        handle = StepHandle(
            runner=self, work=work, tokens=toks, lp_arrays=None,
            rng_before=rng_before,
            postproc=functools.partial(self._verify_rows, work, b),
        )
        handle.chain_vec = nxt
        return handle

    @staticmethod
    def _verify_rows(work: VerifyWork, b: int, mat, lp):
        """Host-side row building for a resolved verify handle: row i's
        usable predictions are its first len(fed) positions."""
        del lp
        return [
            list(map(int, mat[i, : len(work.token_ids[i])]))
            for i in range(b)
        ], None

    def _execute_verify(self, work: VerifyWork) -> list[list[int]]:
        handle = self._dispatch_verify(work)
        rows = handle.resolve()
        self.last_sync_s = handle.sync_s
        return rows

    # -- public API --------------------------------------------------------

    def execute(self, work: ScheduleOutput) -> list[list[int]]:
        """Run one scheduled step; returns one token row per request
        (prefill: [[tok]] if work.sample else [[]]; decode: up to `window`
        candidate tokens per request; verify: argmax at every fed
        position)."""
        if isinstance(work, VerifyWork):
            return self._execute_verify(work)
        handle = self.execute_async(work)
        rows = handle.resolve()
        self.last_logprobs = handle.logprob_rows
        self.last_sync_s = handle.sync_s
        return rows

    def execute_async(
        self, work: ScheduleOutput, prev: StepHandle | None = None
    ) -> StepHandle:
        """Dispatch one step WITHOUT syncing its results — the async
        pipeline's entry point. `prev` is the still-unresolved previous
        decode/verify step; rows whose work.chain_rows entry is >= 0 take
        their input token from its device-resident output (no host round
        trip) — a decode row chains its single input token, a chained
        verify row chains its FIRST fed token (the in-flight verify's
        bonus token; its remaining fed tokens are the host-proposed
        continuation). Resolve the returned handle to get the token
        rows."""
        if isinstance(work, PrefillWork):
            return self._dispatch_prefill(work)
        if isinstance(work, DecodeWork):
            return self._dispatch_decode(work, prev)
        if isinstance(work, VerifyWork):
            return self._dispatch_verify(work, prev)
        raise TypeError(
            f"cannot dispatch {type(work).__name__} asynchronously"
        )

    def _dispatch_prefill(self, work: PrefillWork) -> StepHandle:
        """One dispatch for the whole prefill batch: rows padded to a common
        chunk bucket, batch padded to a power of two. Every row samples at its
        chunk's last token (static shapes); non-sampling rows' tokens are
        discarded host-side."""
        sched = self.config.scheduler
        b = len(work.requests)
        b_pad = self._batch_bucket(b)
        t = max(len(row) for row in work.token_ids)
        t_pad = sched.bucket_for(t, sched.prefill_buckets)
        want_lp = any(
            work.sample[i] and req.sampling.logprobs is not None
            for i, req in enumerate(work.requests)
        )
        want_mt = any(r.sampling.min_tokens > 0 for r in work.requests)
        # structured output: only SAMPLING rows constrain — a mid-prompt
        # chunk produces no token, so its mask would be dead weight
        want_gr = any(
            work.sample[i]
            and req.sampling.grammar is not None
            and req.grammar is not None
            for i, req in enumerate(work.requests)
        )
        nb = self._width_bucket(
            max((len(r.block_table) for r in work.requests), default=1)
        )
        # a first-seen program key pads up to an already-compiled shape
        # instead of stalling serving on a synchronous XLA compile
        exact_key = ("prefill", b_pad, t_pad, nb, want_lp, want_mt, want_gr)
        aot_key = self._pick_prefill_shape(
            b_pad, t_pad, nb, want_lp, want_mt, want_gr
        )
        self._watch_dispatch(exact_key, aot_key)
        _, b_pad, t_pad, nb, _lp, use_mt, use_gr = aot_key

        token_ids = np.zeros((b_pad, t_pad), np.int32)
        positions = np.zeros((b_pad, t_pad), np.int32)
        # per-token slots feed only the sp path's row scatter; the paged path
        # commits blockwise (write_ids below) and takes a placeholder
        slots = (
            np.zeros((b_pad, t_pad), np.int32)  # padding -> null page
            if self._sp > 1
            else None
        )
        context_lens = np.zeros(b_pad, np.int32)
        chunk_lens = np.zeros(b_pad, np.int32)
        # blockwise KV commit: a T_pad chunk starting at worst-case offset
        # bs-1 spans (T_pad-1)//bs + 2 pool pages; padding -> null page
        bs = self.config.cache.block_size
        nbw = (t_pad - 1) // bs + 2
        write_ids = np.zeros((b_pad, nbw), np.int32)
        start_off = np.zeros(b_pad, np.int32)
        sample_rows = np.zeros(b_pad, np.int32)
        temps = np.zeros(b_pad, np.float32)
        top_ps = np.ones(b_pad, np.float32)
        top_ks = np.zeros(b_pad, np.int32)
        seeds: list[int | None] = [None] * b_pad
        counts = np.zeros(b_pad, np.int32)
        for i, req in enumerate(work.requests):
            row = work.token_ids[i]
            token_ids[i, : len(row)] = row
            positions[i, : len(row)] = work.positions[i]
            if slots is not None:
                slots[i, : len(row)] = work.slot_mappings[i]
            context_lens[i] = work.context_lens[i]
            chunk_lens[i] = len(row)
            hist = work.context_lens[i] - len(row)
            first_blk = hist // bs
            n_span = (work.context_lens[i] - 1) // bs - first_blk + 1
            write_ids[i, :n_span] = req.block_table[first_blk : first_blk + n_span]
            start_off[i] = hist % bs
            sample_rows[i] = i * t_pad + len(row) - 1
            s = req.sampling
            temps[i], top_ps[i], top_ks[i] = s.temperature, s.top_p, s.top_k
            seeds[i] = s.seed
            counts[i] = len(req.output_token_ids)
        block_tables = self._block_table_array(
            [r.block_table for r in work.requests], pad_to=b_pad, width=nb
        )
        lora_idx = np.zeros(b_pad, np.int32)
        for i, req in enumerate(work.requests):
            lora_idx[i] = req.lora_index
        min_toks, stop_ids_arr = self._stop_id_arrays(work.requests, b_pad)
        # the prefill sample's admissible-token mask is host-built: the
        # automaton state is host-known (sync replays accepted outputs, so
        # resumed-after-preempt rows land on the right cursor too)
        grammar_mask = None
        if want_gr:
            v = self.config.model.vocab_size
            grammar_mask = np.ones((b_pad, v), dtype=bool)
            for i, req in enumerate(work.requests):
                if (
                    work.sample[i]
                    and req.sampling.grammar is not None
                    and req.grammar is not None
                ):
                    req.grammar.sync(req.output_token_ids)
                    grammar_mask[i] = req.grammar.mask()
            grammar_mask = self._put(grammar_mask, self._batch2)
        elif use_gr:
            # a grammar-enabled program serving an unconstrained batch
            # (shape dominance): all-ones mask is the identity, cached per
            # batch bucket like the zero stop arrays
            grammar_mask = self._ones_mask_cache.get(b_pad)
            if grammar_mask is None:
                grammar_mask = self._put(
                    np.ones(
                        (b_pad, self.config.model.vocab_size), dtype=bool
                    ),
                    self._batch2,
                )
                self._ones_mask_cache[b_pad] = grammar_mask
        tokens_dev, lp_dev, rng_before = self._run(
            token_ids, positions, block_tables,
            slots.reshape(-1) if slots is not None else np.zeros(1, np.int32),
            context_lens, chunk_lens, write_ids, start_off, lora_idx,
            sample_rows, temps, top_ps, top_ks, seeds=seeds, counts=counts,
            min_toks=min_toks, stop_ids_arr=stop_ids_arr,
            grammar_mask=grammar_mask,
            # use_mt may exceed want_mt (an mt=True program serves mt=False
            # batches: suppression is a no-op at min_toks=0); likewise a
            # gr=True program serves gr=False via the all-ones identity mask
            want_logprobs=want_lp, want_min_tokens=use_mt,
            want_grammar=use_gr,
            aot_key=aot_key, watch_reqs=work.requests,
        )
        return StepHandle(
            runner=self, work=work, tokens=tokens_dev, lp_arrays=lp_dev,
            rng_before=rng_before,
            postproc=functools.partial(self._prefill_rows, work, b),
        )

    @staticmethod
    def _prefill_rows(work: PrefillWork, b: int, tokens, lp):
        """Host-side row building for a resolved prefill handle."""
        if lp is None:
            lp_rows = None
        else:
            chosen, top_lp, top_id = lp
            lp_rows = [
                (
                    [(float(chosen[i]),
                      list(map(int, top_id[i])),
                      list(map(float, top_lp[i])))]
                    if work.sample[i]
                    else []
                )
                for i in range(b)
            ]
        rows = [
            [int(tokens[i])] if work.sample[i] else [] for i in range(b)
        ]
        return rows, lp_rows

    def _dispatch_decode(
        self, work: DecodeWork, prev: StepHandle | None = None
    ) -> StepHandle:
        if self._sleeping_params_host is not None:
            raise RuntimeError("engine is sleeping; wake it before running")
        sched = self.config.scheduler
        b = len(work.requests)
        b_pad = sched.bucket_for(b, sched.decode_buckets)
        want_lp = any(
            r.sampling.logprobs is not None for r in work.requests
        )
        want_mt = any(r.sampling.min_tokens > 0 for r in work.requests)
        # structured output: distinct grammars in this batch, by identity
        # (TokenGrammar.uid) — gr_idx maps each row to its table slot
        grammars: list = []
        g_uid_to_slot: dict[int, int] = {}
        row_slots: list[tuple[int, int]] = []  # (row, table slot)
        for i, req in enumerate(work.requests):
            g = req.sampling.grammar
            if g is None or req.grammar is None:
                continue
            slot = g_uid_to_slot.get(g.uid)
            if slot is None:
                slot = len(grammars)
                g_uid_to_slot[g.uid] = slot
                grammars.append(g)
            row_slots.append((i, slot))
        want_gr = bool(grammars)
        gkey = None
        if want_gr:
            # pads are part of the PROGRAM KEY: bigger tables are the same
            # program, so dominance pads tables up instead of recompiling.
            # s_pad strictly exceeds every real state count, which makes
            # row s_pad-1 all -1 — the dead sink rejected transitions park in
            gkey = (
                self._pow2(len(grammars)),
                self._pow2(max(g.n_states for g in grammars) + 1),
                self._pow2(max(g.n_classes for g in grammars)),
            )
        nb = self._width_bucket(
            max((len(r.block_table) for r in work.requests), default=1)
        )
        # never stall a decode window on a first-seen program key
        exact_key = ("decode", b_pad, nb, work.window, want_lp, want_mt, gkey)
        aot_key = self._pick_decode_shape(
            b_pad, nb, work.window, want_lp, want_mt, gkey
        )
        self._watch_dispatch(exact_key, aot_key)
        _, b_pad, nb, _w, _lp, use_mt, use_gkey = aot_key

        first_tokens = np.zeros(b_pad, np.int32)
        first_tokens[:b] = work.token_ids
        ft = self._put(first_tokens, self._batch1)
        chain = work.chain_rows
        idx_dev = None
        if any(c >= 0 for c in chain):
            # chained rows read their input token straight from the
            # previous (still in-flight) step's device output — the
            # D2H→H2D round trip the pipeline eliminates
            if prev is None:
                raise RuntimeError(
                    "decode work chains rows but no previous StepHandle "
                    "was supplied"
                )
            idx = np.full(b_pad, -1, np.int32)
            idx[: len(chain)] = chain
            idx_dev = self._put(idx, self._batch1)
            if prev.chain_vec is not None:  # previous step was a verify
                ft = self._chain_vec_fn(prev.chain_vec, ft, idx_dev)
            else:
                ft = self._chain_fn(prev.tokens, ft, idx_dev)
        positions0 = np.zeros(b_pad, np.int32)
        positions0[:b] = work.positions
        block_tables = self._block_table_array(
            [r.block_table for r in work.requests], pad_to=b_pad, width=nb
        )
        temps = [r.sampling.temperature for r in work.requests] + [0.0] * (b_pad - b)
        top_ps = [r.sampling.top_p for r in work.requests] + [1.0] * (b_pad - b)
        top_ks = [r.sampling.top_k for r in work.requests] + [0] * (b_pad - b)
        seeds = [r.sampling.seed for r in work.requests] + [None] * (b_pad - b)
        # effective output counts: tokens still in flight from the previous
        # step count as generated (seeded-sampling fold and min_tokens
        # suppression must see the serial-world counter); 0 on the sync path
        counts = [
            len(r.output_token_ids) + r.num_inflight_tokens
            for r in work.requests
        ] + [0] * (b_pad - b)

        rng_before = self._rng
        self._rng, step_key = jax.random.split(self._rng)
        has_seed = np.asarray([s is not None for s in seeds], bool)
        seed_vals = np.asarray([(s or 0) & 0xFFFFFFFF for s in seeds], np.uint32)
        lora_idx = np.zeros(b_pad, np.int32)
        for i, req in enumerate(work.requests):
            lora_idx[i] = req.lora_index
        min_toks, stop_ids_arr = self._stop_id_arrays(work.requests, b_pad)
        dyn_args = (
            ft,
            self._put(positions0, self._batch1),
            self._put(block_tables, self._batch2),
            self._put(lora_idx, self._batch1) if self._use_lora else None,
            self._put(np.asarray(temps, np.float32), self._batch1),
            self._put(np.asarray(top_ps, np.float32), self._batch1),
            self._put(np.asarray(top_ks, np.int32), self._batch1),
            self._put(step_key, self._rep),
            self._put(seed_vals, self._batch1),
            self._put(has_seed, self._batch1),
            self._put(np.asarray(counts, np.int32), self._batch1),
            self._put(min_toks, self._batch1),
            self._put(stop_ids_arr, self._batch2),
        )
        if want_gr:
            tc_dev, cd_dev, acc_dev = self._grammar_device_tables(
                grammars, use_gkey
            )
            gr_idx = np.full(b_pad, -1, np.int32)
            gs0 = np.zeros(b_pad, np.int32)
            grammar_chains = False
            for i, slot in row_slots:
                gr_idx[i] = slot
                req = work.requests[i]
                if chain[i] >= 0:
                    # input token is still in flight: the row's entering
                    # state rides the previous handle's device-side
                    # grammar_states vector instead of the host cursor
                    grammar_chains = True
                else:
                    req.grammar.sync(req.output_token_ids)
                    # a dead host cursor (-1) maps to the device dead sink
                    # (last padded state row) — never to a clamped index
                    gs0[i] = (
                        req.grammar.state
                        if req.grammar.state >= 0
                        else use_gkey[1] - 1
                    )
            gs_dev = self._put(gs0, self._batch1)
            if grammar_chains:
                if prev is None or prev.grammar_states is None:
                    raise RuntimeError(
                        "constrained decode rows chain on a step without "
                        "grammar states (scheduler must not chain grammar "
                        "rows onto verify or unconstrained steps)"
                    )
                # same gather as token chaining: rows with idx >= 0 read
                # the in-flight step's post-window state, others keep the
                # host value (non-grammar chained rows gather junk their
                # gr_idx = -1 makes inert)
                gs_dev = self._chain_vec_fn(
                    prev.grammar_states, gs_dev, idx_dev
                )
            if self._gr_eos_dev is None:
                self._gr_eos_dev = self._put(
                    np.asarray([grammars[0].eos_token_id], np.int32),
                    self._rep,
                )
            dyn_args = dyn_args + (
                tc_dev, cd_dev, acc_dev,
                self._put(gr_idx, self._batch1),
                gs_dev,
                self._gr_eos_dev,
            )
        aot = self._aot_exec.get(aot_key)
        if aot is not None:
            result = aot(
                self.params, self.lora_params, self.kv_caches, *dyn_args
            )
        else:
            with self._bg_lock:
                new_program = aot_key not in self._compiled_keys
            t0 = time.perf_counter()
            result = self._decode_window_fn(
                self.params,
                self.lora_params,
                self.kv_caches,
                *dyn_args,
                window=work.window,
                want_logprobs=want_lp,
                want_min_tokens=use_mt,
                want_grammar=want_gr,
            )
            self._note_compiled(aot_key)
            if new_program:
                self._watch_sync_compile(
                    "decode", aot_key, time.perf_counter() - t0,
                    work.requests,
                )
        gstates = None
        if want_lp and want_gr:
            self.kv_caches, tokens, lp_arrays, gstates = result
        elif want_lp:
            self.kv_caches, tokens, lp_arrays = result
        elif want_gr:
            self.kv_caches, tokens, gstates = result
            lp_arrays = None
        else:
            self.kv_caches, tokens = result
            lp_arrays = None
        handle = StepHandle(
            runner=self, work=work, tokens=tokens, lp_arrays=lp_arrays,
            rng_before=rng_before,
            postproc=functools.partial(self._decode_rows, work, b),
        )
        handle.grammar_states = gstates
        return handle

    @staticmethod
    def _decode_rows(work: DecodeWork, b: int, mat, lp):
        """Host-side row building for a resolved decode handle."""
        if lp is None:
            lp_rows = None
        else:
            lp_w, top_lp_w, top_id_w = lp
            # python-ify only the rows that asked — the device already
            # computed the whole batch, but 256x32x8 tuple-building on the
            # host for rows the engine will ignore is pure waste
            lp_rows = [
                (
                    [
                        (float(lp_w[i, k]),
                         list(map(int, top_id_w[i, k])),
                         list(map(float, top_lp_w[i, k])))
                        for k in range(work.window)
                    ]
                    if req.sampling.logprobs is not None
                    else []
                )
                for i, req in enumerate(work.requests)
            ]
        return [list(map(int, mat[i])) for i in range(b)], lp_rows

    # -- helpers -----------------------------------------------------------

    def _run(
        self, token_ids, positions, block_tables, slots, context_lens,
        chunk_lens, write_ids, start_off, lora_idx, sample_rows, temps,
        top_ps, top_ks, seeds, counts, min_toks, stop_ids_arr,
        grammar_mask=None,  # device (B, V) bool when want_grammar
        want_logprobs=False, want_min_tokens=False, want_grammar=False,
        aot_key=None, watch_reqs=None,
    ):
        if self._sleeping_params_host is not None:
            raise RuntimeError("engine is sleeping; wake it before running")
        rng_before = self._rng
        self._rng, step_key = jax.random.split(self._rng)
        has_seed = np.asarray([s is not None for s in seeds], bool)
        # 64-bit user seeds (legal per the OpenAI API) fold down to uint32
        seed_vals = np.asarray(
            [(s or 0) & 0xFFFFFFFF for s in seeds], np.uint32
        )
        # sp shards the chunk axis; dp-only meshes leave T unsharded
        tok_sh = self._seq2 if self._sp > 1 else self._batch2
        dyn_args = (
            self._put(token_ids, tok_sh),
            self._put(positions, tok_sh),
            self._put(block_tables, self._batch2),
            # (B*T,) for the sp path (B divisible by dp); (1,) placeholder
            # (replicated) for the paged path
            self._put(slots, self._batch1 if self._sp > 1 else self._rep),
            self._put(context_lens, self._batch1),
            self._put(chunk_lens, self._batch1),
            self._put(write_ids, self._batch2),
            self._put(start_off, self._batch1),
            self._put(lora_idx, self._batch1) if self._use_lora else None,
            self._put(sample_rows, self._batch1),
            self._put(np.asarray(temps, np.float32), self._batch1),
            self._put(np.asarray(top_ps, np.float32), self._batch1),
            self._put(np.asarray(top_ks, np.int32), self._batch1),
            self._put(step_key, self._rep),
            self._put(seed_vals, self._batch1),
            self._put(has_seed, self._batch1),
            self._put(np.asarray(counts, np.int32), self._batch1),
            self._put(min_toks, self._batch1),
            self._put(stop_ids_arr, self._batch2),
        )
        if want_grammar:
            dyn_args = dyn_args + (grammar_mask,)  # already device-resident
        aot = self._aot_exec.get(aot_key) if aot_key is not None else None
        if aot is not None:
            result = aot(
                self.params, self.lora_params, self.kv_caches, *dyn_args
            )
        else:
            # a first-ever key means this _step_fn call traces+compiles
            # synchronously — the stall CompileWatch attributes to the
            # batch that blocked on it (must check BEFORE the call:
            # _note_compiled below adds the key)
            new_program = False
            if aot_key is not None:
                with self._bg_lock:
                    new_program = aot_key not in self._compiled_keys
            t0 = time.perf_counter()
            result = self._step_fn(
                self.params,
                self.lora_params,
                self.kv_caches,
                *dyn_args,
                want_logprobs=want_logprobs,
                want_min_tokens=want_min_tokens,
                want_grammar=want_grammar,
            )
            if aot_key is not None:
                self._note_compiled(aot_key)
                if new_program:
                    self._watch_sync_compile(
                        "prefill", aot_key, time.perf_counter() - t0,
                        watch_reqs,
                    )
        if want_logprobs:
            self.kv_caches, tokens, lp = result
        else:
            self.kv_caches, tokens = result
            lp = None
        # NO host sync here: the caller wraps these in a StepHandle whose
        # resolve() performs the single batched D2H transfer
        return tokens, lp, rng_before

    def _grammar_device_tables(self, grammars: list, gkey: tuple):
        """Replicated device copies of the batch's automaton tables, padded
        to the program key's (G, S, C) buckets: token_class (G, V) int32,
        class_dest (G, S, C) int32 (-1 = reject; padding rows/cols all -1,
        so state S-1 is the guaranteed dead sink), accepting (G, S) bool.
        Cached by (grammar uids, pads) — a steady constrained batch
        re-dispatches with zero table H2D traffic."""
        g_pad, s_pad, c_pad = gkey
        key = (tuple(g.uid for g in grammars), g_pad, s_pad, c_pad)
        hit = self._grammar_tables_cache.get(key)
        if hit is not None:
            return hit
        t0 = time.perf_counter()
        v = self.config.model.vocab_size
        tc = np.zeros((g_pad, v), np.int32)
        cd = np.full((g_pad, s_pad, c_pad), -1, np.int32)
        acc = np.zeros((g_pad, s_pad), bool)
        for j, g in enumerate(grammars):
            if g.vocab_size != v:
                raise RuntimeError(
                    f"grammar lifted over vocab {g.vocab_size}, model has {v}"
                )
            tc[j] = g.token_class
            cd[j, : g.n_states, : g.n_classes] = g.class_dest
            acc[j, : g.n_states] = g.accepting
        out = (
            self._put(tc, self._rep),
            self._put(cd, self._rep),
            self._put(acc, self._rep),
        )
        # bounded: distinct (batch composition, pads) combos churn during
        # warmup then stabilize; evict oldest past a small cap
        if len(self._grammar_tables_cache) >= 32:
            self._grammar_tables_cache.pop(
                next(iter(self._grammar_tables_cache))
            )
        self._grammar_tables_cache[key] = out
        # telemetry: table builds are numpy-side (no XLA program) but they
        # sit on the dispatch path — the watch inventories them under
        # phase="grammar", excluded from cache hit/miss and storm counting
        self.compile_watch.record_build(
            "grammar", ("grammar", key[0], g_pad, s_pad, c_pad),
            time.perf_counter() - t0,
            "mid_traffic" if self.fallback_enabled else "warmup",
            role=self.compile_role,
        )
        return out

    def _stop_id_arrays(self, requests, pad_to: int):
        """(min_toks (B,), stop_ids (B, SUPPRESS_IDS)) for device-side
        min_tokens suppression (sampling.suppress_stop_tokens): eos first
        (unless ignore_eos), then stop_token_ids; -1 pads. Batches with no
        min_tokens rows (the steady state) reuse cached zero arrays — this
        runs on every dispatch."""
        if not any(r.sampling.min_tokens > 0 for r in requests):
            cached = self._zero_stop_arrays.get(pad_to)
            if cached is None:
                cached = (
                    np.zeros(pad_to, np.int32),
                    np.full((pad_to, SUPPRESS_IDS), -1, np.int32),
                )
                self._zero_stop_arrays[pad_to] = cached
            return cached
        min_toks = np.zeros(pad_to, np.int32)
        stop_ids = np.full((pad_to, SUPPRESS_IDS), -1, np.int32)
        for i, req in enumerate(requests):
            s = req.sampling
            if s.min_tokens <= 0:
                continue
            min_toks[i] = s.min_tokens
            ids = []
            if not s.ignore_eos and req.eos_token_id is not None:
                ids.append(req.eos_token_id)
            ids.extend(s.stop_token_ids)
            for j, tid in enumerate(ids[:SUPPRESS_IDS]):
                stop_ids[i, j] = tid
        return min_toks, stop_ids

    # -- compile-stall avoidance -------------------------------------------
    #
    # The measured live-serving collapse mode (ROUND3.md): traffic's first
    # hit on a new (rows × chunk × width) program key froze serving for a
    # 30-60s XLA compile while queued work starved, and the warmup ladder
    # cannot enumerate the full key crossproduct in reasonable boot time.
    # Structural fix: a program-key MISS never compiles on the hot path
    # when any already-compiled program DOMINATES the needed shape (every
    # axis >= needed) — padding further up is semantically identical, just
    # more compute — and the exact program is AOT-compiled concurrently on
    # a background thread (jit.lower().compile() traces/compiles without
    # executing; XLA compiles release the GIL, so serving dispatches
    # continue). Once ready, the next hit dispatches the specialized
    # executable. Serving therefore starts after warming only a COARSE
    # shape lattice and migrates to exact programs under live traffic with
    # zero stalls.

    @property
    def _dynamic_programs_ok(self) -> bool:
        # the sp prefill path has its own step fn and shardings; the
        # fallback machinery covers the common paged path
        return self._sp == 1

    def _sds(self, shape, dtype, sharding):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    def _aval_tree(self, tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=x.sharding
            ),
            tree,
        )

    def _pick_prefill_shape(
        self, b_pad: int, t_pad: int, nb: int, want_lp: bool, want_mt: bool,
        want_gr: bool = False,
    ) -> tuple:
        """The program KEY to dispatch with: exact when that program is
        compiled (or nothing compiled dominates it — cold start compiles
        synchronously); otherwise the cheapest compiled dominating key,
        with the exact program queued for background compile.

        Dominance: every shape axis >= needed; want_logprobs must match
        exactly (it changes the output structure); a want_min_tokens=True
        program dominates False (suppression is a no-op at min_toks=0); a
        grammar-enabled program dominates a plain one (the all-ones mask is
        the identity)."""
        key = ("prefill", b_pad, t_pad, nb, want_lp, want_mt, want_gr)
        if not self._dynamic_programs_ok or not self.fallback_enabled:
            return key
        with self._bg_lock:
            if key in self._compiled_keys:
                return key
            candidates = [
                k for k in self._compiled_keys
                if k[0] == "prefill" and k[4] == want_lp and k[5] >= want_mt
                and k[6] >= want_gr
                and k[1] >= b_pad and k[2] >= t_pad and k[3] >= nb
            ]
        if not candidates:
            return key
        self.compile_fallbacks += 1
        self._bg_compile(key)
        return min(candidates, key=lambda k: (k[1] * k[2], k[3], k[5], k[6]))

    @staticmethod
    def _gkey_dominates(have, need) -> bool:
        """Grammar-table pads (G, S, C) dominate componentwise; None (no
        grammar path compiled in) serves only None — the output structures
        differ (grammar programs return the state vector). Tables pad UP to
        the candidate's buckets, so a bigger-table program is the same
        program."""
        if need is None:
            return have is None
        return have is not None and all(a >= b for a, b in zip(have, need))

    def _pick_decode_shape(
        self, b_pad: int, nb: int, window: int, want_lp: bool, want_mt: bool,
        gkey: tuple | None = None,
    ) -> tuple:
        """Like _pick_prefill_shape for the fused decode window. `window`
        is never substituted: it is semantic (tokens generated, pool blocks
        the scheduler reserved) — a larger window would scatter past the
        reserved blocks."""
        key = ("decode", b_pad, nb, window, want_lp, want_mt, gkey)
        if not self._dynamic_programs_ok or not self.fallback_enabled:
            return key
        with self._bg_lock:
            if key in self._compiled_keys:
                return key
            candidates = [
                k for k in self._compiled_keys
                if k[0] == "decode" and k[3] == window
                and k[4] == want_lp and k[5] >= want_mt
                and self._gkey_dominates(k[6], gkey)
                and k[1] >= b_pad and k[2] >= nb
            ]
        if not candidates:
            return key
        self.compile_fallbacks += 1
        self._bg_compile(key)
        return min(candidates, key=lambda k: (k[1], k[2], k[5]))

    def _note_compiled(self, key: tuple) -> None:
        with self._bg_lock:
            self._compiled_keys.add(key)

    def _watch_dispatch(self, exact_key: tuple, aot_key: tuple) -> None:
        """Program-cache hit/miss accounting: a HIT is the exact requested
        key already compiled (no pad-up, no sync compile). The dispatch is
        charged to the key actually served."""
        watch = self.compile_watch
        if not watch.enabled:
            return
        with self._bg_lock:
            hit = aot_key == exact_key and exact_key in self._compiled_keys
        watch.record_dispatch(aot_key, hit, role=self.compile_role)

    def _watch_sync_compile(
        self, phase: str, key: tuple, wall_s: float, requests
    ) -> None:
        """A program compiled ON the dispatch path. During warmup
        (fallback disabled, every wave compiles its exact program) that is
        the plan; mid-traffic it is the stall the pad-up cache exists to
        prevent — recorded against the requests whose step blocked, and
        stamped onto each request for its trace timeline."""
        watch = self.compile_watch
        if not watch.enabled:
            return
        trigger = "mid_traffic" if self.fallback_enabled else "warmup"
        rid = None
        if requests:
            rid = getattr(requests[0], "request_id", None)
        watch.record_build(
            phase, key, wall_s, trigger, rid=rid, role=self.compile_role,
        )
        if trigger != "mid_traffic" or not requests:
            return
        stall = {
            "phase": phase,
            "key": repr(tuple(key)),
            "wall_ms": round(wall_s * 1000.0, 1),
        }
        for req in requests:
            stalls = getattr(req, "compile_stalls", None)
            if stalls is None:
                req.compile_stalls = [dict(stall)]
            else:
                stalls.append(dict(stall))

    def _bg_compile(self, key: tuple) -> None:
        with self._bg_lock:
            if key in self._bg_inflight or key in self._compiled_keys:
                return
            self._bg_inflight.add(key)
            if self._bg_executor is None:
                self._bg_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="xla-bg-compile"
                )
        self._bg_executor.submit(self._bg_compile_job, key)

    def shutdown(self, wait: bool = False) -> None:
        """Cancel queued background compiles — each is a 30-60s XLA compile
        behind an idle-gate sleep, and concurrent.futures' atexit hook
        would otherwise drain them all before the interpreter can exit.
        A job already running is signalled to bail too: without the stop
        event it would sit in the idle-gate sleep (up to 10 min) and fire
        its compile exactly when the process next goes quiet — observed as
        stolen CPU (pacing flakes) in whatever test module runs next.
        wait=True additionally blocks until the in-flight job (which XLA
        cannot interrupt) finishes — test teardowns use it so no compile
        thread ever outlives its module."""
        self._bg_stop.set()
        with self._bg_lock:
            ex, self._bg_executor = self._bg_executor, None
        if ex is not None:
            ex.shutdown(wait=wait, cancel_futures=True)

    def _bg_compile_job(self, key: tuple) -> None:
        try:
            # idle gate: wait (bounded) for a traffic lull before compiling.
            # On remote-device links the compile service contends with
            # dispatch — compiling during traffic steals the serving time
            # this thread exists to protect (measured ~10x prefill dispatch
            # inflation with compiles in flight)
            idle = self.idle_check
            if idle is not None:
                import time as _time

                deadline = _time.monotonic() + 600.0
                while not idle():
                    if self._bg_stop.is_set() or _time.monotonic() > deadline:
                        return  # still busy; the key stays un-compiled and
                        # the fallback keeps absorbing it
                    _time.sleep(0.25)
            if self._bg_stop.is_set():
                return
            hb = self.heartbeat
            if hb is not None:
                hb.beat()  # busy from here: the compile itself can wedge
            if self._compile_key_now(key):
                self.bg_compiles += 1
                logger.info(
                    "background-compiled %s program %s", key[0], key[1:]
                )
        except Exception:
            logger.exception("background compile failed for %s", key)
        finally:
            hb = self.heartbeat
            if hb is not None:
                hb.idle()
            with self._bg_lock:
                self._bg_inflight.discard(key)

    def _compile_key_now(self, key: tuple, trigger: str = "bg") -> bool:
        """AOT-compile one program key (.lower().compile() — traces and
        compiles WITHOUT executing: no tokens, no pool writes, no pool
        capacity requirement). Returns True when a new executable landed."""
        with self._bg_lock:
            if key in self._compiled_keys:
                return False
        if self._sleeping_params_host is not None or self.kv_caches is None:
            return False  # parked; avals unavailable — compiles lazily later
        # avals, not live arrays: the step thread donates kv_caches every
        # dispatch, and lowering must not race buffer invalidation
        params_av = self._aval_tree(self.params)
        lora_av = (
            self._aval_tree(self.lora_params) if self._use_lora else None
        )
        kv_av = self._aval_tree(self.kv_caches)
        if key[0] == "prefill":
            _, b, t, nb, want_lp, want_mt, want_gr = key
            lowered = self._step_fn.lower(
                params_av, lora_av, kv_av,
                *self._prefill_avals(b, t, nb, want_gr),
                want_logprobs=want_lp, want_min_tokens=want_mt,
                want_grammar=want_gr,
            )
        else:
            _, b, nb, window, want_lp, want_mt, gkey = key
            lowered = self._decode_window_fn.lower(
                params_av, lora_av, kv_av,
                *self._decode_avals(b, nb, gkey),
                window=window, want_logprobs=want_lp,
                want_min_tokens=want_mt, want_grammar=gkey is not None,
            )
        t0 = time.perf_counter()
        compiled = lowered.compile()
        wall = time.perf_counter() - t0
        with self._bg_lock:
            self._aot_exec[key] = compiled
            self._compiled_keys.add(key)
        self.compile_watch.record_build(
            key[0], key, wall, trigger, role=self.compile_role,
            memory_bytes=program_memory_bytes(compiled),
        )
        return True

    def precompile_dominating(self) -> int:
        """Compile the DOMINATING program lattice directly: full batch ×
        each chunk bucket × the TOP width bucket for prefill, plus the top
        decode bucket × top width × each pow2 window. Shapes are virtual
        (no execution), so this works regardless of pool-vs-max_model_len
        sizing. Afterwards every finer program key has a pad-up fallback —
        serving cannot stall on a synchronous compile. This is the
        engine's warmup(scope=\"coarse\")."""
        if not self._dynamic_programs_ok:
            return 0
        sched = self.config.scheduler
        top_w = self._width_bucket(self.max_blocks)
        b_top = self._batch_bucket(sched.max_num_seqs)
        t_top = max(sched.prefill_buckets)
        n = 0
        for t in sorted(set(sched.prefill_buckets)):
            if self._compile_key_now(("prefill", b_top, t, top_w,
                                      False, False, False), "warmup"):
                n += 1
        # the pow2 ROWS ladder at (top chunk, top width): rows are the
        # expensive padding axis (each padded row computes t_pad tokens of
        # dense FLOPs), so a few extra programs here turn the worst-case
        # fallback from "jump to full batch" (up to max_num_seqs/rows x
        # compute) into "pad width only" (~1.2x HBM)
        b = 1
        while b < b_top:
            if self._compile_key_now(("prefill", b, t_top, top_w,
                                      False, False, False), "warmup"):
                n += 1
            b *= 2
        top_window = 1
        w = 1
        while w <= sched.decode_window:
            top_window = w
            for d in sorted(set(sched.decode_buckets)):
                if d > sched.max_num_seqs:
                    continue  # unreachable batch bucket
                if self._compile_key_now(("decode", d, top_w, w,
                                          False, False, None), "warmup"):
                    n += 1
            w *= 2
        # min_tokens variants at the top shapes: an mt=True program
        # DOMINATES mt=False (suppression no-ops at min_toks=0), so these
        # two keep even min_tokens traffic stall-free after a coarse boot
        d_top = max(
            (d for d in sched.decode_buckets if d <= sched.max_num_seqs),
            default=min(sched.decode_buckets),
        )
        for key in (
            ("prefill", b_top, t_top, top_w, False, True, False),
            ("decode", d_top, top_w, top_window, False, True, None),
        ):
            if self._compile_key_now(key, "warmup"):
                n += 1
        logger.info("precompiled %d dominating programs", n)
        return n

    def _prefill_avals(self, b: int, t: int, nb: int, want_gr: bool = False):
        """ShapeDtypeStructs mirroring _run's dynamic args for one prefill
        shape — MUST stay in lockstep with the _step_fn call in _run."""
        bs = self.config.cache.block_size
        nbw = (t - 1) // bs + 2
        i32, f32 = jnp.int32, jnp.float32
        b1, b2, rep = self._batch1, self._batch2, self._rep
        s = self._sds
        v = self.config.model.vocab_size
        gr = ((s((b, v), jnp.bool_, b2),) if want_gr else ())  # grammar_mask
        return (
            s((b, t), i32, b2),       # token_ids
            s((b, t), i32, b2),       # positions
            s((b, nb), i32, b2),      # block_tables
            s((1,), i32, rep),        # slots placeholder (paged path)
            s((b,), i32, b1),         # context_lens
            s((b,), i32, b1),         # chunk_lens
            s((b, nbw), i32, b2),     # write_ids
            s((b,), i32, b1),         # start_off
            s((b,), i32, b1) if self._use_lora else None,  # lora_idx
            s((b,), i32, b1),         # sample_rows
            s((b,), f32, b1),         # temperature
            s((b,), f32, b1),         # top_p
            s((b,), i32, b1),         # top_k
            s(self._rng.shape, self._rng.dtype, rep),  # rng
            s((b,), jnp.uint32, b1),  # seeds
            s((b,), jnp.bool_, b1),   # has_seed
            s((b,), i32, b1),         # counts
            s((b,), i32, b1),         # min_toks
            s((b, SUPPRESS_IDS), i32, b2),  # stop_ids
        ) + gr

    def _decode_avals(self, b: int, nb: int, gkey: tuple | None = None):
        """ShapeDtypeStructs mirroring _dispatch_decode's dynamic args —
        MUST stay in lockstep with the _decode_window_fn call."""
        i32, f32 = jnp.int32, jnp.float32
        b1, b2, rep = self._batch1, self._batch2, self._rep
        s = self._sds
        v = self.config.model.vocab_size
        gr = ()
        if gkey is not None:
            g, sp, cp = gkey
            gr = (
                s((g, v), i32, rep),       # gr_token_class
                s((g, sp, cp), i32, rep),  # gr_class_dest
                s((g, sp), jnp.bool_, rep),  # gr_accepting
                s((b,), i32, b1),          # gr_idx
                s((b,), i32, b1),          # gr_state0
                s((1,), i32, rep),         # gr_eos
            )
        return (
            s((b,), i32, b1),         # first_tokens
            s((b,), i32, b1),         # positions0
            s((b, nb), i32, b2),      # block_tables
            s((b,), i32, b1) if self._use_lora else None,  # lora_idx
            s((b,), f32, b1),         # temperature
            s((b,), f32, b1),         # top_p
            s((b,), i32, b1),         # top_k
            s(self._rng.shape, self._rng.dtype, rep),  # base_key
            s((b,), jnp.uint32, b1),  # seeds
            s((b,), jnp.bool_, b1),   # has_seed
            s((b,), i32, b1),         # counts0
            s((b,), i32, b1),         # min_toks
            s((b, SUPPRESS_IDS), i32, b2),  # stop_ids
        ) + gr

    @staticmethod
    def _pow2(n: int) -> int:
        """Next power of two — bounds compiled program count to log2 sizes."""
        return 1 << max(0, n - 1).bit_length()

    def _batch_bucket(self, b: int) -> int:
        """Batch rows pad to a power of two ≥ dp so the batch axis shards
        evenly (dp is validated to be a power of two)."""
        return max(self._dp, self._pow2(b))

    def _put(self, x, sharding):
        """Place a host array directly into its mesh sharding — one
        host→shards transfer, no staging hop through the default device
        (dp=1 meshes take the same path, so there is one path to test)."""
        return jax.device_put(x, sharding)

    def _width_bucket(self, longest: int) -> int:
        """Block-table width bucket for the widest table in a batch: pow2
        with a configurable FLOOR (default 64 blocks ≈ 1k tokens): every
        width is its own compiled program, and the fine-grained ladder
        below the floor bought little (short-context gathers are cheap to
        pad) while costing a compile per boundary crossing. Benches with
        exactly-warmed shapes set width_floor_blocks=1."""
        floor = self.config.scheduler.width_floor_blocks
        return max(1, min(max(floor, self._pow2(longest)), self.max_blocks))

    def _block_table_array(
        self,
        tables: list[list[int]],
        pad_to: int | None = None,
        width: int | None = None,
    ) -> np.ndarray:
        """(B, nb) table where nb is the *bucketed max blocks in use* — not
        max_model_len/block_size. The gathered context is nb*block_size wide,
        so sizing nb to the batch's real context (round-1 weak #2: the full
        max-len gather per layer per step was the dominant waste) cuts HBM
        traffic by max_model_len/actual_len; power-of-two nb keeps the
        compiled-program set logarithmic. `width` overrides the bucket (the
        compile-fallback path pads to an already-compiled width)."""
        b = pad_to or len(tables)
        longest = max((len(t) for t in tables), default=1)
        nb = width if width is not None else self._width_bucket(longest)
        arr = np.zeros((b, nb), np.int32)  # 0 = null page
        for i, tbl in enumerate(tables):
            arr[i, : len(tbl)] = tbl
        return arr

    # -- embeddings (/v1/embeddings; plain encode, no paged KV) ------------

    def embed(self, rows: list[list[int]]) -> np.ndarray:
        """L2-normalized last-token embeddings for a batch of token rows.
        Rows group by prefill bucket and batch into ONE dispatch per
        (batch-bucket, length-bucket) pair — same compile-bounding
        discipline as the serving steps, and no per-row device round-trips.
        Returns (N, hidden) float32."""
        if self._sleeping_params_host is not None:
            raise RuntimeError("engine is sleeping; wake it before running")
        if self._embed_fn is None:
            self._embed_fn = jax.jit(
                lambda p, ids, lens: llama.embed_encode(
                    self.config.model, p, ids, lens
                )
            )
        out = np.zeros(
            (len(rows), self.config.model.hidden_size), np.float32
        )
        # pow2 length buckets up to max_model_len — embeddings must accept
        # anything the model's context fits (the scheduler's prefill buckets
        # cap chunk sizes, not document lengths), with a log2-bounded
        # compiled-program set
        groups: dict[int, list[int]] = {}
        for idx, row in enumerate(rows):
            t_pad = min(
                self._pow2(len(row)), self.config.model.max_model_len
            )
            groups.setdefault(t_pad, []).append(idx)
        for t_pad, idxs in groups.items():
            b_pad = self._batch_bucket(len(idxs))
            ids = np.zeros((b_pad, t_pad), np.int32)
            lens = np.ones(b_pad, np.int32)  # padding rows pool token 0
            for j, idx in enumerate(idxs):
                ids[j, : len(rows[idx])] = rows[idx]
                lens[j] = len(rows[idx])
            vecs = self._embed_fn(
                self.params,
                self._put(ids, self._batch2),
                self._put(lens, self._batch1),
            )
            got = np.asarray(jax.device_get(vecs))
            for j, idx in enumerate(idxs):
                out[idx] = got[j]
        return out

    # -- host KV tier transfers (engine/kv_host_tier.py) -------------------

    def fetch_block(self, blk: int) -> list[jax.Array]:
        """HBM→host, non-blocking: slice one block's pages per layer and
        start their host copies. The caller (HostKVTier) resolves the
        transfer to numpy later — offloads happen inside the scheduler loop
        with the engine lock held, so blocking here would stall a device
        round-trip per evicted block (the transfer instead overlaps the next
        step's compute)."""
        if self._fetch_block_fn is None:
            self._fetch_block_fn = jax.jit(
                lambda kv, blk: tuple(leaf[:, blk] for leaf in kv)
            )
        parts = self._fetch_block_fn(self.kv_caches, jnp.int32(blk))
        for p in parts:
            p.copy_to_host_async()
        return list(parts)

    def upload_block(self, blk: int, data: np.ndarray) -> None:
        """Host→HBM: write offloaded pages into block `blk` in place."""
        if self._upload_block_fn is None:

            @functools.partial(jax.jit, donate_argnames=("kv_caches",))
            def upload_fn(kv_caches, data, blk):
                return tuple(
                    leaf.at[:, blk].set(data[i].astype(leaf.dtype))
                    for i, leaf in enumerate(kv_caches)
                )

            self._upload_block_fn = upload_fn
        self.kv_caches = self._upload_block_fn(
            self.kv_caches, data, jnp.int32(blk)
        )

    def upload_blocks(self, blks: list[int], data: np.ndarray) -> None:
        """Host→HBM for N blocks in ONE device dispatch — the PD import /
        remote-fetch path. Per-block upload_block costs a dispatch round
        trip each (ruinous through high-RTT tunnels: 512 blocks of an 8k
        prompt ≈ 512 RTTs); this is one scatter for the whole group. `data`
        is (N, L, 2, block_size, kvH, D). N is padded up to a power of two
        (duplicating the last row — duplicate scatter indices with identical
        payloads are benign) so arbitrary run lengths compile at most
        log2(max) program variants instead of one per N."""
        n = len(blks)
        bucket = 1
        while bucket < n:
            bucket *= 2
        if bucket != n:
            blks = list(blks) + [blks[-1]] * (bucket - n)
            data = np.concatenate(
                [data, np.repeat(data[-1:], bucket - n, axis=0)]
            )
        if getattr(self, "_upload_blocks_fn", None) is None:

            @functools.partial(jax.jit, donate_argnames=("kv_caches",))
            def upload_many_fn(kv_caches, data, blks):
                return tuple(
                    leaf.at[:, blks].set(
                        jnp.swapaxes(data[:, i], 0, 1).astype(leaf.dtype)
                    )
                    for i, leaf in enumerate(kv_caches)
                )

            self._upload_blocks_fn = upload_many_fn
        self.kv_caches = self._upload_blocks_fn(
            self.kv_caches, np.ascontiguousarray(data),
            jnp.asarray(blks, jnp.int32),
        )

    # -- LoRA slots --------------------------------------------------------

    def install_lora(self, slot: int, adapter) -> None:
        """Write a parsed adapter (models/lora_loader.LoRAAdapter) into slot
        buffers on device. Same shapes every time — no recompile."""
        assert self._use_lora and 1 <= slot < self.config.lora.num_slots
        lp = self.lora_params
        for name, mod in lp.items():
            if name == "scale":
                continue
            if name in adapter.modules:
                a = jnp.asarray(adapter.modules[name]["A"], mod["A"].dtype)
                b = jnp.asarray(adapter.modules[name]["B"], mod["B"].dtype)
            else:  # module not targeted by this adapter: zero its delta
                a = jnp.zeros_like(mod["A"][slot])
                b = jnp.zeros_like(mod["B"][slot])
            mod["A"] = mod["A"].at[slot].set(a)
            mod["B"] = mod["B"].at[slot].set(b)
        lp["scale"] = lp["scale"].at[slot].set(adapter.scale)
        self.lora_params = jax.device_put(lp, self._lora_shardings)

    def remove_lora(self, slot: int) -> None:
        """Free a slot: zeroing its scale makes every delta exactly 0."""
        assert self._use_lora and 1 <= slot < self.config.lora.num_slots
        self.lora_params["scale"] = (
            self.lora_params["scale"].at[slot].set(0.0)
        )

    # -- sleep / wake (reference: router /sleep proxying, request.py:434-510;
    #    vLLM sleep levels; SURVEY §7.3 hard part 3) ------------------------

    @property
    def is_sleeping(self) -> bool:
        return self._sleeping_params_host is not None

    def sleep(self, level: int = 1) -> None:
        """Park the engine: move weights to host RAM (level 1) or drop them
        (level 2 — wake() re-inits from config), freeing HBM."""
        if self.is_sleeping:
            return
        if level >= 2 and not self._random_weights:
            # level 2 re-inits on wake; with loaded checkpoints that would
            # silently swap trained weights for random ones
            raise RuntimeError(
                "sleep level 2 requires re-initializable weights; use level 1 "
                "for checkpoint-loaded models"
            )
        if level >= 2:
            self._sleeping_params_host = "discarded"
        else:
            self._sleeping_params_host = jax.device_get(self.params)
        self.params = None
        # LoRA buffers are HBM-resident too (num_slots × L × 7 modules);
        # sleep's whole point is reclaiming HBM, so park them alongside
        if self.lora_params is not None:
            self._sleeping_lora_host = jax.device_get(self.lora_params)
            self.lora_params = None
        # drop the KV pool too; sleeping engines are drained by the router
        self.kv_caches = None

    def _param_shardings(self):
        """NamedSharding tree for the (possibly quantized) param tree."""
        cfg = self.config.model
        specs = llama_param_specs(cfg)
        if cfg.quantization:
            from ..models.quantization import quantize_specs

            specs = quantize_specs(cfg, specs)
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs)

    def _init_device_params(self, shardings):
        """Random-init (and quantize, when configured) in ONE compiled
        program straight into the sharded HBM layout — XLA frees each bf16
        leaf as soon as its int8 twin exists, so the peak stays near the
        int8 tree, never the full bf16 tree."""
        cfg = self.config.model

        def build(c, key):
            p = llama.init_params(c, key)
            if c.quantization:
                from ..models.quantization import quantize_params

                p = quantize_params(c, p)
            return p

        init_fn = jax.jit(build, static_argnums=0, out_shardings=shardings)
        return init_fn(cfg, jax.random.PRNGKey(self.config.seed))

    def wake(self) -> None:
        if not self.is_sleeping:
            return
        cfg = self.config
        param_shardings = self._param_shardings()
        if isinstance(self._sleeping_params_host, str):  # discarded
            self.params = self._init_device_params(param_shardings)
        else:
            self.params = jax.tree.map(
                jax.device_put, self._sleeping_params_host, param_shardings
            )
        if self._sleeping_lora_host is not None:
            self.lora_params = jax.device_put(
                self._sleeping_lora_host, self._lora_shardings
            )
            self._sleeping_lora_host = None
        self.kv_caches = jax.jit(
            lambda: llama.init_kv_cache(
                cfg.model, cfg.cache.num_blocks, cfg.cache.block_size,
                dtype=self._kv_dtype,
            ),
            out_shardings=NamedSharding(self.mesh, kv_cache_spec()),
        )()
        self._sleeping_params_host = None
