"""Engine /metrics exporter (Prometheus text format).

Exports the TPU metric contract (metrics_contract.py) the router scraper and
the observability stack consume — the HBM equivalent of the vLLM names the
reference scrapes (engine_stats.py:63-76). Names keep the `tpu:` prefix
(colons are valid Prometheus metric name characters, same convention as
vLLM's `vllm:` metrics)."""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.core import HistogramMetricFamily
from prometheus_client.openmetrics import exposition as om_exposition

from .. import metrics_contract as mc
from .engine import EngineStatsSnapshot
from .kv_flow import (
    DIRECTIONS,
    HYDRATION_SOURCES,
    TRANSFER_SECONDS_BUCKETS,
    TRANSFER_TIERS,
)
from .saturation import OCCUPANCY_BUCKETS, STEP_WALL_BUCKETS, WASTE_REASONS

OPENMETRICS_CONTENT_TYPE = om_exposition.CONTENT_TYPE_LATEST


def wants_openmetrics(request) -> bool:
    """/metrics?format=openmetrics serves the OpenMetrics exposition — the
    one that renders histogram exemplars (trace ids on buckets). An
    explicit query param, NOT Accept-header negotiation: OpenMetrics
    forbids colons, so prometheus_client rewrites `tpu:` to `tpu_` there,
    and honoring Prometheus's default Accept preference would silently
    rename the whole scrape contract out from under every dashboard."""
    return request.query.get("format") == "openmetrics"


def _hist_family(
    name: str, doc: str, labelnames: list[str]
) -> HistogramMetricFamily:
    return HistogramMetricFamily(name, doc, labels=labelnames)


def _cum_buckets(hist: dict) -> list[tuple[str, float]]:
    """(le, cumulative-count) pairs (incl. +Inf) from a StepMeter _Hist
    snapshot's per-bucket counts."""
    out: list[tuple[str, float]] = []
    running = 0
    counts = hist.get("counts") or []
    bounds = list(hist.get("buckets") or ()) + [float("inf")]
    for le, n in zip(bounds, counts):
        running += n
        out.append(("+Inf" if le == float("inf") else repr(float(le)),
                    float(running)))
    if not out:
        out = [("+Inf", 0.0)]
    return out


class _SaturationHistograms:
    """Custom collector rendering the StepMeter's per-step distributions
    (tpu:engine_step_occupancy, tpu:engine_step_wall_seconds) straight
    from the cumulative bucket counts the snapshot carries — the step
    thread increments plain ints; no prometheus objects ride the hot
    path."""

    _EMPTY_OCC = {"buckets": OCCUPANCY_BUCKETS,
                  "counts": [0] * (len(OCCUPANCY_BUCKETS) + 1),
                  "sum": 0.0, "count": 0}
    _EMPTY_WALL = {"buckets": STEP_WALL_BUCKETS,
                   "counts": [0] * (len(STEP_WALL_BUCKETS) + 1),
                   "sum": 0.0, "count": 0}

    def __init__(self, owner: "EngineMetrics"):
        self._owner = owner

    def collect(self):
        sat = self._owner.saturation or {}
        model = self._owner.model_name
        occ = _hist_family(
            mc.ENGINE_STEP_OCCUPANCY,
            "Decode-seat occupancy (rows / max_num_seqs) per resolved "
            "decode step",
            ["model_name"],
        )
        h = sat.get("occupancy_hist") or self._EMPTY_OCC
        occ.add_metric([model], _cum_buckets(h), h.get("sum", 0.0))
        yield occ
        wall = _hist_family(
            mc.ENGINE_STEP_WALL,
            "Resolve-cadence wall seconds per resolved step, by phase",
            ["model_name", "phase"],
        )
        walls = sat.get("step_wall_hist") or {}
        for phase in ("prefill", "decode"):
            h = walls.get(phase) or self._EMPTY_WALL
            wall.add_metric(
                [model, phase], _cum_buckets(h), h.get("sum", 0.0)
            )
        yield wall


class _KVFlowHistograms:
    """Custom collector rendering the KVFlowMeter's per-(tier, direction)
    transfer-latency distribution (tpu:kv_transfer_seconds) straight from
    the cumulative bucket counts its snapshot carries — same pattern as
    _SaturationHistograms: the transfer paths increment plain ints; no
    prometheus objects ride the engine/writer threads. Every (tier,
    direction) combo renders from the first scrape (closed label sets,
    seeded at zero)."""

    _EMPTY = {"buckets": TRANSFER_SECONDS_BUCKETS,
              "counts": [0] * (len(TRANSFER_SECONDS_BUCKETS) + 1),
              "sum": 0.0, "count": 0}

    def __init__(self, owner: "EngineMetrics"):
        self._owner = owner

    def collect(self):
        flow = self._owner.kv_flow or {}
        model = self._owner.model_name
        fam = _hist_family(
            mc.KV_TRANSFER_SECONDS,
            "Wall seconds per KV tier-transfer batch, by tier and "
            "direction (in = toward HBM / hydration, out = offload)",
            ["model_name", "tier", "direction"],
        )
        hists = flow.get("seconds_hist") or {}
        for tier in TRANSFER_TIERS:
            for direction in DIRECTIONS:
                h = hists.get(f"{tier}/{direction}") or self._EMPTY
                fam.add_metric(
                    [model, tier, direction],
                    _cum_buckets(h), h.get("sum", 0.0),
                )
        yield fam


class EngineMetrics:
    def __init__(self, model_name: str):
        self.registry = CollectorRegistry()
        self.model_name = model_name
        # latest snapshot's saturation dict, read by the histogram
        # collector at scrape time (update() refreshes it first)
        self.saturation: dict = {}
        # latest snapshot's kv_flow dict (the transfer-latency histogram
        # collector reads it at scrape time)
        self.kv_flow: dict = {}
        self._labels = {"model_name": model_name}
        names = list(self._labels)

        def gauge(name: str, doc: str) -> Gauge:
            return Gauge(name, doc, names, registry=self.registry)

        def counter(name: str, doc: str) -> Counter:
            # prometheus_client re-appends _total to counter names
            base = name[: -len("_total")] if name.endswith("_total") else name
            return Counter(base, doc, names, registry=self.registry)

        self.num_running = gauge(
            mc.NUM_REQUESTS_RUNNING, "Requests currently decoding"
        )
        self.num_waiting = gauge(
            mc.NUM_REQUESTS_WAITING, "Requests queued or prefilling"
        )
        self.kv_usage = gauge(
            mc.HBM_KV_USAGE_PERC, "Fraction of HBM KV pages in active use"
        )
        self.prefix_hit_rate = gauge(
            mc.PREFIX_CACHE_HIT_RATE, "Prefix cache block hit rate"
        )
        self.prefix_hits = counter(mc.PREFIX_CACHE_HITS, "Prefix cache block hits")
        self.prefix_queries = counter(
            mc.PREFIX_CACHE_QUERIES, "Prefix cache block queries"
        )
        self.preemptions = counter(mc.NUM_PREEMPTIONS, "Scheduler preemptions")
        self.host_kv_usage = gauge(
            mc.HOST_KV_USAGE_PERC, "Fraction of host-RAM KV tier in use"
        )
        self.step_overlap = gauge(
            mc.STEP_OVERLAP_FRAC,
            "Fraction of step-loop wall time overlapping device execution",
        )
        self.host_offloads = counter(
            mc.HOST_KV_OFFLOADS, "KV blocks offloaded HBM to host RAM"
        )
        self.host_reloads = counter(
            mc.HOST_KV_RELOADS, "KV blocks reloaded host RAM to HBM"
        )
        self.remote_stores = counter(
            mc.REMOTE_KV_STORES, "KV blocks pushed to the remote store"
        )
        self.remote_fetches = counter(
            mc.REMOTE_KV_FETCHES, "KV blocks fetched from the remote store"
        )
        self.spec_draft = counter(
            mc.SPEC_DRAFT_TOKENS, "Speculative tokens proposed (all proposers)"
        )
        self.spec_accepted = counter(
            mc.SPEC_ACCEPTED_TOKENS, "Speculative tokens accepted"
        )
        # per-proposer acceptance accounting (docs/36): proposer= is the
        # closed ngram|draft set, seeded below so the acceptance-rate rule
        # has both series from the first scrape
        self.spec_proposed_by = Counter(
            mc.SPEC_PROPOSED_TOKENS[: -len("_total")],
            "Speculative tokens proposed, by proposer (closed label set: "
            + ", ".join(mc.SPEC_PROPOSER_VALUES) + ")",
            [*names, "proposer"],
            registry=self.registry,
        )
        self.spec_accepted_by = Counter(
            mc.SPEC_ACCEPTED_BY_PROPOSER[: -len("_total")],
            "Speculative tokens accepted at verification, by proposer",
            [*names, "proposer"],
            registry=self.registry,
        )
        for proposer in mc.SPEC_PROPOSER_VALUES:
            self.spec_proposed_by.labels(**self._labels, proposer=proposer)
            self.spec_accepted_by.labels(**self._labels, proposer=proposer)
        self.prompt_tokens = counter(mc.PROMPT_TOKENS, "Prompt tokens processed")
        self.generation_tokens = counter(mc.GENERATION_TOKENS, "Tokens generated")
        self.requests_shed = counter(
            mc.REQUESTS_SHED, "Requests refused 429 by admission control"
        )
        self.deadline_expired = counter(
            mc.REQUESTS_DEADLINE_EXPIRED,
            "Requests shed at admission or aborted mid-decode on deadline",
        )
        self.draining = gauge(
            mc.ENGINE_DRAINING, "1 while the engine is draining"
        )
        # -- saturation & goodput (docs/29-saturation-slo.md) -------------
        self.seat_occupancy = gauge(
            mc.ENGINE_DECODE_SEAT_OCCUPANCY,
            "Decode-seat occupancy EWMA (rows in the resolved decode "
            "dispatch / max_num_seqs)",
        )
        self.padding_waste = gauge(
            mc.ENGINE_PADDING_WASTE_FRAC,
            "Fraction of device-computed token slots that were bucket "
            "padding (EWMA)",
        )
        self.achieved_flops = gauge(
            mc.ENGINE_ACHIEVED_FLOPS,
            "Achieved forward-pass FLOP/s (analytic model estimate, "
            "resolve-cadence EWMA)",
        )
        self.mfu = gauge(
            mc.ENGINE_MFU,
            "Model FLOPs utilization estimate (achieved / chip peak; 0 "
            "when the peak is unknown)",
        )
        self.kv_tier_usage = Gauge(
            mc.ENGINE_KV_TIER_USAGE,
            "KV occupancy per cache tier (hbm / host / disk / remote)",
            [*names, "tier"],
            registry=self.registry,
        )

        def pcounter(name: str, doc: str) -> Counter:
            base = name[: -len("_total")] if name.endswith("_total") else name
            return Counter(base, doc, [*names, "phase"],
                           registry=self.registry)

        self.step_tokens = pcounter(
            mc.ENGINE_STEP_TOKENS,
            "Useful tokens processed per phase (prefill chunk tokens / "
            "decode host-accepted tokens)",
        )
        self.padded_tokens = pcounter(
            mc.ENGINE_PADDED_TOKENS,
            "Device-computed token slots per phase, including bucket "
            "padding",
        )
        self.model_flops = counter(
            mc.ENGINE_MODEL_FLOPS,
            "Cumulative analytic forward-pass FLOPs",
        )
        self.goodput_tokens = counter(
            mc.GOODPUT_TOKENS,
            "Sampled tokens delivered to a successfully finished request",
        )
        self.wasted_tokens = Counter(
            mc.WASTED_TOKENS[: -len("_total")],
            "Sampled tokens wasted, by reason (closed label set: "
            + ", ".join(WASTE_REASONS) + ")",
            [*names, "reason"],
            registry=self.registry,
        )
        # seed the closed label sets at zero so every series exists from
        # the first scrape (rate() over a counter that appears mid-flight
        # misses its first increment)
        for phase in ("prefill", "decode"):
            self.step_tokens.labels(**self._labels, phase=phase)
            self.padded_tokens.labels(**self._labels, phase=phase)
        for reason in WASTE_REASONS:
            self.wasted_tokens.labels(**self._labels, reason=reason)
        self.goodput_tokens.labels(**self._labels)
        self.model_flops.labels(**self._labels)
        for tier in ("hbm", "host", "disk", "remote"):
            self.kv_tier_usage.labels(**self._labels, tier=tier)
        self.registry.register(_SaturationHistograms(self))
        # -- KV flow telemetry (docs/30-kv-flow-telemetry.md) -------------
        flabels = [*names, "tier", "direction"]

        def fcounter(name: str, doc: str) -> Counter:
            base = name[: -len("_total")] if name.endswith("_total") else name
            return Counter(base, doc, flabels, registry=self.registry)

        self.kv_transfer_bytes = fcounter(
            mc.KV_TRANSFER_BYTES,
            "Bytes moved between KV tiers, by tier and direction (in = "
            "toward HBM / hydration, out = offload)",
        )
        self.kv_transfer_blocks = fcounter(
            mc.KV_TRANSFER_BLOCKS,
            "KV blocks moved between tiers, by tier and direction",
        )
        self.kv_transfer_logical_bytes = fcounter(
            mc.KV_TRANSFER_LOGICAL_BYTES,
            "Logical (decoded) bytes the tier transfers represent — "
            "kv_transfer_bytes counts WIRE bytes, so with an at-rest KV "
            "codec (docs/38-kv-quantization.md) this series is larger by "
            "the compression ratio; identical without one",
        )
        self.kv_tier_compression = Gauge(
            mc.KV_TIER_COMPRESSION_RATIO,
            "At-rest KV codec effectiveness per (tier, direction): "
            "logical bytes / wire bytes moved (1.0 with no codec)",
            flabels,
            registry=self.registry,
        )
        self.kv_tier_bandwidth = Gauge(
            mc.KV_TIER_BANDWIDTH,
            "Recent-mean transfer bandwidth per (tier, direction) — the "
            "measured fetch-GB/s half of the compute-or-load hydration "
            "signal",
            flabels,
            registry=self.registry,
        )
        self.prefix_tokens = Counter(
            mc.REQUEST_PREFIX_TOKENS[: -len("_total")],
            "Prompt tokens by hydration source (closed label set: "
            + ", ".join(HYDRATION_SOURCES)
            + ") — an audited partition: the sum over sources equals the "
            "prompt tokens of admitted requests",
            [*names, "source"],
            registry=self.registry,
        )
        self.disk_stores = counter(
            mc.DISK_KV_STORES, "KV blocks persisted to the local-disk tier"
        )
        self.disk_loads = counter(
            mc.DISK_KV_LOADS, "KV blocks loaded from the local-disk tier"
        )
        self.kv_bytes_per_token = gauge(
            mc.KV_BYTES_PER_TOKEN,
            "Analytic KV bytes per token of this engine's pool "
            "(block_bytes / block_size) — the constant the router's "
            "priced route-vs-migrate scoring multiplies by matched prefix "
            "tokens to price a peer migration (docs/35-peer-kv-reuse.md)",
        )
        self.hydration_decisions = Counter(
            mc.KV_HYDRATION_DECISIONS[: -len("_total")],
            "Compute-or-load hydration planner chunk decisions (closed "
            "label set: " + ", ".join(mc.KV_HYDRATION_CHOICES)
            + ") — fallback_recompute = a load chunk that missed its "
            "fetch deadline or whose fetch failed",
            [*names, "choice"],
            registry=self.registry,
        )
        # seed the closed label sets at zero (same rationale as the
        # saturation series: rate() over a counter appearing mid-flight
        # misses its first increment)
        for tier in TRANSFER_TIERS:
            for direction in DIRECTIONS:
                fl = {**self._labels, "tier": tier, "direction": direction}
                self.kv_transfer_bytes.labels(**fl)
                self.kv_transfer_blocks.labels(**fl)
                self.kv_transfer_logical_bytes.labels(**fl)
                self.kv_tier_compression.labels(**fl).set(1.0)
                self.kv_tier_bandwidth.labels(**fl)
        for source in HYDRATION_SOURCES:
            self.prefix_tokens.labels(**self._labels, source=source)
        for choice in mc.KV_HYDRATION_CHOICES:
            self.hydration_decisions.labels(**self._labels, choice=choice)
        self.disk_stores.labels(**self._labels)
        self.disk_loads.labels(**self._labels)
        self.kv_bytes_per_token.labels(**self._labels)
        self.registry.register(_KVFlowHistograms(self))
        # -- fleet-coherence telemetry (docs/32-fleet-telemetry.md) --------
        # session-stickiness audit (fleet.SessionStickinessAudit): closed
        # reason set, seeded so both series exist from the first scrape
        self.stickiness_violations = Counter(
            mc.SESSION_STICKINESS_VIOLATIONS[: -len("_total")],
            "Session-affinity violations detected engine-side (closed "
            "reason set: " + ", ".join(mc.STICKINESS_REASON_VALUES)
            + ") — zero with one router replica and stable membership",
            [*names, "reason"],
            registry=self.registry,
        )
        for reason in mc.STICKINESS_REASON_VALUES:
            self.stickiness_violations.labels(**self._labels, reason=reason)
        # KV event publisher health: the PUBLISHER vantage on a failing
        # event path (a dying publisher used to be visible only as
        # controller-side resync storms)
        self.kv_event_batches = counter(
            mc.KV_EVENT_PUBLISH_BATCHES,
            "KV event batches POSTed to the index subscriber (incl. "
            "heartbeats and snapshots)",
        )
        self.kv_event_failures = counter(
            mc.KV_EVENT_PUBLISH_FAILURES,
            "KV event publish rounds that failed (transport fault or "
            "subscriber error)",
        )
        self.kv_event_queue_depth = gauge(
            mc.KV_EVENT_QUEUE_DEPTH,
            "KV events buffered awaiting flush (pinned at capacity = the "
            "publisher cannot keep up and a resync gap is imminent)",
        )
        self.kv_event_subscribers = gauge(
            mc.KV_EVENT_SUBSCRIBERS,
            "Subscribers this engine's KV event publisher fans batches out "
            "to (the controller, embedded-index router replicas, or both; "
            "0 = no publisher configured)",
        )
        self.kv_event_batches.labels(**self._labels)
        self.kv_event_failures.labels(**self._labels)
        self.kv_event_queue_depth.labels(**self._labels).set(0)
        self.kv_event_subscribers.labels(**self._labels).set(0)
        # -- flight recorder & thread-liveness watchdog (docs/37-flight-
        # recorder.md): per-loop heartbeat age (thread= closed set; 0 for
        # loops not running in this deployment) and stall episodes by kind
        self.thread_heartbeat_age = Gauge(
            mc.THREAD_HEARTBEAT_AGE,
            "Seconds since each long-lived loop's last liveness beat "
            "(closed thread set: " + ", ".join(mc.THREAD_NAME_VALUES)
            + "; 0 = loop not running in this deployment) — a busy loop "
            "whose age passes its threshold is a named wedge",
            [*names, "thread"],
            registry=self.registry,
        )
        self.step_stalls = Counter(
            mc.ENGINE_STEP_STALLS[: -len("_total")],
            "Watchdog stall episodes by kind (closed set: "
            + ", ".join(mc.STALL_KIND_VALUES)
            + ") — counted once per episode, not per check round",
            [*names, "kind"],
            registry=self.registry,
        )
        for thread in mc.THREAD_NAME_VALUES:
            self.thread_heartbeat_age.labels(
                **self._labels, thread=thread
            ).set(0)
        for kind in mc.STALL_KIND_VALUES:
            self.step_stalls.labels(**self._labels, kind=kind)
        # -- pool rebalancing (docs/40-pool-rebalancing.md): the engine's
        # live prefill/decode role — 1 on the current role, both 0 when
        # the engine serves no disaggregated pool. The router's stats
        # scraper follows this instead of the frozen helm model label.
        self.pool_role = Gauge(
            mc.POOL_ROLE,
            "Live prefill/decode pool role (closed role set: "
            + ", ".join(mc.POOL_ROLE_VALUES)
            + "; 1 on the current role, both 0 without one)",
            [*names, "role"],
            registry=self.registry,
        )
        for role in mc.POOL_ROLE_VALUES:
            self.pool_role.labels(**self._labels, role=role).set(0)
        # -- structured output (docs/41-structured-output.md): finished
        # constrained requests by outcome (closed set) plus the grammar
        # compile-time histogram (cache hits do not observe)
        self.structured_requests = Counter(
            mc.STRUCTURED_REQUESTS[: -len("_total")],
            "Finished structured-output requests by outcome (closed set: "
            + ", ".join(mc.STRUCTURED_OUTCOME_VALUES)
            + ") — valid means the terminal automaton state was accepting",
            [*names, "outcome"],
            registry=self.registry,
        )
        for outcome in mc.STRUCTURED_OUTCOME_VALUES:
            self.structured_requests.labels(**self._labels, outcome=outcome)
        self.grammar_build_time = Histogram(
            mc.GRAMMAR_BUILD_TIME,
            "Wall seconds to compile one grammar into token-class tables "
            "(schema -> byte-DFA -> token lift); grammar-cache hits skip "
            "this entirely",
            names,
            buckets=(
                0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
            ),
            registry=self.registry,
        )
        # -- XLA compile telemetry (docs/42-compile-telemetry.md): program
        # builds by (phase, trigger), compile walls, and the program-cache
        # inventory/hit/miss view — every (phase, trigger) series seeded so
        # rate() works from the first mid-traffic compile
        self.engine_compiles = Counter(
            mc.ENGINE_COMPILES[: -len("_total")],
            "Program (and grammar-table) builds by phase and trigger "
            "(closed sets: " + ", ".join(mc.COMPILE_PHASE_VALUES) + " x "
            + ", ".join(mc.COMPILE_TRIGGER_VALUES)
            + ") — trigger=mid_traffic is a dispatch-path stall",
            [*names, "phase", "trigger"],
            registry=self.registry,
        )
        for phase in mc.COMPILE_PHASE_VALUES:
            for trigger in mc.COMPILE_TRIGGER_VALUES:
                self.engine_compiles.labels(
                    **self._labels, phase=phase, trigger=trigger
                )
        self.compile_seconds = Histogram(
            mc.ENGINE_COMPILE_SECONDS,
            "Wall seconds per program build (all triggers; real-model XLA "
            "compiles run 30-60s)",
            names,
            buckets=mc.COMPILE_SECONDS_BUCKETS,
            registry=self.registry,
        )
        self.compile_seconds.labels(**self._labels)
        self.program_cache_programs = Gauge(
            mc.ENGINE_PROGRAM_CACHE_PROGRAMS,
            "Programs in the CompileWatch inventory (compiled and "
            "retained)",
            names,
            registry=self.registry,
        )
        self.program_cache_programs.labels(**self._labels).set(0)
        self.program_cache_hits = Counter(
            mc.ENGINE_PROGRAM_CACHE_HITS[: -len("_total")],
            "Dispatches whose exact program key was already compiled",
            names,
            registry=self.registry,
        )
        self.program_cache_misses = Counter(
            mc.ENGINE_PROGRAM_CACHE_MISSES[: -len("_total")],
            "Dispatches that padded up to a dominating program or "
            "compiled synchronously",
            names,
            registry=self.registry,
        )
        self.compile_storms = Counter(
            mc.ENGINE_COMPILE_STORMS[: -len("_total")],
            "Recompile-storm episodes (threshold mid-traffic compiles "
            "inside the sliding window; one bump per episode)",
            names,
            registry=self.registry,
        )
        for c in (self.program_cache_hits, self.program_cache_misses,
                  self.compile_storms):
            c.labels(**self._labels)
        # -- multi-tenant QoS (docs/27-multitenancy.md): tenant-labeled
        # series; cardinality bounded by qos.TenantAccounting.MAX_TENANTS
        tlabels = [*names, "tenant"]

        def tcounter(name: str, doc: str) -> Counter:
            base = name[: -len("_total")] if name.endswith("_total") else name
            return Counter(base, doc, tlabels, registry=self.registry)

        self.tenant_requests = tcounter(
            mc.TENANT_REQUESTS, "Requests admitted per tenant"
        )
        self.tenant_tokens = tcounter(
            mc.TENANT_GENERATION_TOKENS, "Tokens generated per tenant"
        )
        self.tenant_shed = tcounter(
            mc.TENANT_SHED,
            "Requests shed per tenant (admission refusals + lowest-"
            "priority-first queue evictions)",
        )
        self.tenant_queue_wait = Histogram(
            mc.TENANT_QUEUE_WAIT,
            "Seconds from submission to first scheduler seat, per tenant",
            tlabels,
            buckets=(
                0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0,
            ),
            registry=self.registry,
        )
        # -- per-request phase histograms (docs/28-request-tracing.md):
        # observed at request finish from the lifecycle stamps the tracing
        # spine attributes, with trace-id exemplars (OpenMetrics exposition)
        def phase_hist(name: str, doc: str) -> Histogram:
            return Histogram(
                name, doc, names,
                buckets=mc.REQUEST_PHASE_BUCKETS, registry=self.registry,
            )

        self.request_ttft = phase_hist(
            mc.REQUEST_TTFT, "Arrival to first generated token"
        )
        self.request_e2e = phase_hist(
            mc.REQUEST_E2E, "Arrival to request finish"
        )
        self.request_queue_wait = phase_hist(
            mc.REQUEST_QUEUE_WAIT, "Arrival to first scheduler seat"
        )
        self.request_prefill = phase_hist(
            mc.REQUEST_PREFILL, "First seat to first generated token"
        )
        self.request_decode = phase_hist(
            mc.REQUEST_DECODE, "First generated token to finish"
        )
        self._counter_values: dict[str, int] = {}

    @staticmethod
    def phase_durations(phases: dict) -> dict[str, float]:
        """{metric-suffix: seconds} from a terminal output's lifecycle
        stamps (engine.RequestOutput.phase_times). Phases that never
        happened (shed before a seat, no token before abort) are absent —
        a refusal must not log a 0-second decode."""
        arrival = phases.get("arrival")
        seat = phases.get("first_seat")
        first_tok = phases.get("first_token")
        finish = phases.get("finish")
        if arrival is None or finish is None:
            return {}
        out = {"e2e": max(0.0, finish - arrival)}
        if seat is not None:
            out["queue_wait"] = max(0.0, seat - arrival)
        if first_tok is not None:
            out["ttft"] = max(0.0, first_tok - arrival)
            out["decode"] = max(0.0, finish - first_tok)
            if seat is not None:
                out["prefill"] = max(0.0, first_tok - seat)
        return out

    def observe_request(self, phases: dict, trace_id: str | None = None) -> None:
        """Feed one finished request's phase durations into the contract
        histograms, tagging each bucket with the trace id as an exemplar
        so a dashboard outlier links straight to /debug/requests?rid=."""
        exemplar = {"trace_id": trace_id} if trace_id else None
        hists = {
            "ttft": self.request_ttft,
            "e2e": self.request_e2e,
            "queue_wait": self.request_queue_wait,
            "prefill": self.request_prefill,
            "decode": self.request_decode,
        }
        for key, seconds in self.phase_durations(phases).items():
            hists[key].labels(**self._labels).observe(seconds, exemplar=exemplar)

    def update(self, s: EngineStatsSnapshot) -> None:
        lb = self._labels
        self.num_running.labels(**lb).set(s.num_requests_running)
        self.num_waiting.labels(**lb).set(s.num_requests_waiting)
        self.kv_usage.labels(**lb).set(s.kv_usage_perc)
        self.prefix_hit_rate.labels(**lb).set(s.prefix_cache_hit_rate)
        self._bump(self.prefix_hits, "hits", s.prefix_cache_hits)
        self._bump(self.prefix_queries, "queries", s.prefix_cache_queries)
        self._bump(self.preemptions, "preempt", s.num_preemptions)
        self.host_kv_usage.labels(**lb).set(s.host_kv_usage_perc)
        self.step_overlap.labels(**lb).set(s.step_overlap_frac)
        self._bump(self.host_offloads, "host_off", s.host_kv_offloads)
        self._bump(self.host_reloads, "host_re", s.host_kv_reloads)
        self._bump(self.remote_stores, "remote_store", s.remote_kv_stores)
        self._bump(
            self.remote_fetches, "remote_fetch", s.remote_kv_fetched_blocks
        )
        self._bump(self.spec_draft, "spec_draft", s.spec_draft_tokens)
        self._bump(self.spec_accepted, "spec_acc", s.spec_accepted_tokens)
        for proposer in mc.SPEC_PROPOSER_VALUES:
            pl = {**lb, "proposer": proposer}
            self._bump_labeled(
                self.spec_proposed_by, f"spec_prop:{proposer}",
                int((s.spec_proposed_by or {}).get(proposer, 0)), pl,
            )
            self._bump_labeled(
                self.spec_accepted_by, f"spec_accby:{proposer}",
                int((s.spec_accepted_by or {}).get(proposer, 0)), pl,
            )
        self._bump(self.prompt_tokens, "prompt", s.prompt_tokens)
        self._bump(self.generation_tokens, "gen", s.generation_tokens)
        self._bump(self.requests_shed, "shed", s.requests_shed)
        self._bump(
            self.deadline_expired, "deadline", s.requests_deadline_expired
        )
        self.draining.labels(**lb).set(1 if s.draining else 0)
        for tenant, c in s.tenants.items():
            tl = {**lb, "tenant": tenant}
            self._bump_labeled(
                self.tenant_requests, f"t_req:{tenant}",
                int(c.get("requests", 0)), tl,
            )
            self._bump_labeled(
                self.tenant_tokens, f"t_tok:{tenant}",
                int(c.get("generation_tokens", 0)), tl,
            )
            self._bump_labeled(
                self.tenant_shed, f"t_shed:{tenant}",
                int(c.get("shed", 0)), tl,
            )
        for tenant, seconds in s.tenant_queue_waits:
            # observations were DRAINED from the accounting by stats() —
            # each lands in the histogram exactly once
            self.tenant_queue_wait.labels(**lb, tenant=tenant).observe(
                seconds
            )
        # -- structured output (docs/41-structured-output.md) --------------
        for outcome in mc.STRUCTURED_OUTCOME_VALUES:
            self._bump_labeled(
                self.structured_requests, f"structured:{outcome}",
                int((s.structured_outcomes or {}).get(outcome, 0)),
                {**lb, "outcome": outcome},
            )
        for seconds in (s.grammar_build_times or []):
            # drained from the grammar cache by stats() — each compile
            # lands in the histogram exactly once
            self.grammar_build_time.labels(**lb).observe(seconds)
        # -- XLA compile telemetry (docs/42-compile-telemetry.md) ----------
        comp = s.compile or {}
        if comp.get("enabled"):
            self.program_cache_programs.labels(**lb).set(
                int(comp.get("programs", 0))
            )
            builds = comp.get("compiles") or {}
            for phase in mc.COMPILE_PHASE_VALUES:
                for trigger in mc.COMPILE_TRIGGER_VALUES:
                    self._bump_labeled(
                        self.engine_compiles, f"compile:{phase}/{trigger}",
                        int(builds.get(f"{phase}/{trigger}", 0)),
                        {**lb, "phase": phase, "trigger": trigger},
                    )
            for seconds in (comp.get("walls") or []):
                # drained from the watch by stats() — one observation per
                # build
                self.compile_seconds.labels(**lb).observe(seconds)
            self._bump(
                self.program_cache_hits, "pc_hits", int(comp.get("hits", 0))
            )
            self._bump(
                self.program_cache_misses, "pc_miss",
                int(comp.get("misses", 0)),
            )
            self._bump(
                self.compile_storms, "storms", int(comp.get("storms", 0))
            )
        # -- saturation & goodput (docs/29-saturation-slo.md) -------------
        sat = s.saturation or {}
        self.saturation = sat  # histogram collector reads this at scrape
        self.seat_occupancy.labels(**lb).set(
            sat.get("decode_seat_occupancy", 0.0)
        )
        self.padding_waste.labels(**lb).set(
            sat.get("padding_waste_frac", 0.0)
        )
        self.achieved_flops.labels(**lb).set(
            sat.get("achieved_flops_per_s", 0.0)
        )
        self.mfu.labels(**lb).set(sat.get("mfu", 0.0))
        for tier, frac in (sat.get("kv_tiers") or {}).items():
            self.kv_tier_usage.labels(**lb, tier=tier).set(frac)
        for phase in ("prefill", "decode"):
            self._bump_labeled(
                self.step_tokens, f"step_tok:{phase}",
                int((sat.get("step_tokens") or {}).get(phase, 0)),
                {**lb, "phase": phase},
            )
            self._bump_labeled(
                self.padded_tokens, f"pad_tok:{phase}",
                int((sat.get("padded_tokens") or {}).get(phase, 0)),
                {**lb, "phase": phase},
            )
        self._bump(
            self.model_flops, "model_flops",
            sat.get("model_flops_total", 0.0),
        )
        good = sat.get("goodput") or {}
        self._bump(self.goodput_tokens, "goodput", good.get("delivered", 0))
        wasted = good.get("wasted") or {}
        for reason in WASTE_REASONS:
            # the CLOSED reason set bounds label cardinality by
            # construction — every reason series exists from first scrape
            self._bump_labeled(
                self.wasted_tokens, f"wasted:{reason}",
                int(wasted.get(reason, 0)), {**lb, "reason": reason},
            )
        # -- KV flow telemetry (docs/30-kv-flow-telemetry.md) -------------
        flow = s.kv_flow or {}
        self.kv_flow = flow  # histogram collector reads this at scrape
        fbytes = flow.get("bytes") or {}
        fblocks = flow.get("blocks") or {}
        flogical = flow.get("logical_bytes") or {}
        fratio = flow.get("compression_ratio") or {}
        fbw = flow.get("bandwidth_bytes_per_s") or {}
        fmeas = flow.get("bandwidth_measured") or {}
        for tier in TRANSFER_TIERS:
            for direction in DIRECTIONS:
                key = f"{tier}/{direction}"
                fl = {**lb, "tier": tier, "direction": direction}
                self._bump_labeled(
                    self.kv_transfer_bytes, f"kvb:{key}",
                    int(fbytes.get(key, 0)), fl,
                )
                self._bump_labeled(
                    self.kv_transfer_blocks, f"kvn:{key}",
                    int(fblocks.get(key, 0)), fl,
                )
                self._bump_labeled(
                    self.kv_transfer_logical_bytes, f"kvl:{key}",
                    int(flogical.get(key, 0)), fl,
                )
                # logical/wire over the whole run (1.0 with no codec or no
                # bytes) — the at-rest codec's effectiveness gauge
                self.kv_tier_compression.labels(**fl).set(
                    fratio.get(key, 1.0)
                )
                # gauge gated on the TierBandwidth sample floor: below it
                # the estimate is one tiny transfer's noise, and scrapers
                # (the router's migrate pricing above all) must read 0 =
                # "not measured", exactly what the planner trusts
                self.kv_tier_bandwidth.labels(**fl).set(
                    fbw.get(key, 0.0) if fmeas.get(key) else 0.0
                )
        hyd = flow.get("hydration") or {}
        for source in HYDRATION_SOURCES:
            self._bump_labeled(
                self.prefix_tokens, f"hyd:{source}",
                int(hyd.get(source, 0)), {**lb, "source": source},
            )
        decisions = flow.get("decisions") or {}
        for choice in mc.KV_HYDRATION_CHOICES:
            self._bump_labeled(
                self.hydration_decisions, f"hyd_dec:{choice}",
                int(decisions.get(choice, 0)), {**lb, "choice": choice},
            )
        self._bump(self.disk_stores, "disk_store", s.disk_kv_stores)
        self._bump(self.disk_loads, "disk_load", s.disk_kv_loads)
        self.kv_bytes_per_token.labels(**lb).set(s.kv_bytes_per_token)

    def update_fleet_health(
        self,
        publish_batches: int = 0,
        publish_failures: int = 0,
        pending_depth: int = 0,
        subscribers: int = 0,
        stickiness: dict[str, int] | None = None,
    ) -> None:
        """Fleet-coherence series owned by the HTTP server rather than the
        engine snapshot (docs/32-fleet-telemetry.md): KV event publisher
        health counters, the fan-out subscriber count, and the
        stickiness-audit violation counts, bumped delta-style from their
        monotonic owners at scrape time."""
        self._bump(self.kv_event_batches, "kvev_batches", publish_batches)
        self._bump(self.kv_event_failures, "kvev_failures", publish_failures)
        self.kv_event_queue_depth.labels(**self._labels).set(pending_depth)
        self.kv_event_subscribers.labels(**self._labels).set(subscribers)
        for reason, total in (stickiness or {}).items():
            if reason in mc.STICKINESS_REASON_VALUES:
                self._bump_labeled(
                    self.stickiness_violations, f"sticky:{reason}",
                    int(total), {**self._labels, "reason": reason},
                )

    def update_liveness(
        self,
        ages: dict[str, float] | None = None,
        stall_counts: dict[str, int] | None = None,
    ) -> None:
        """Thread-liveness series (docs/37-flight-recorder.md), computed by
        the EXPORTER from the registry's beat stamps at scrape time — a
        dead watchdog cannot freeze its own age gauge. Unregistered loops
        read 0 (not running here); stall counts bump delta-style from the
        watchdog's monotonic episode counters."""
        ages = ages or {}
        for thread in mc.THREAD_NAME_VALUES:
            self.thread_heartbeat_age.labels(
                **self._labels, thread=thread
            ).set(ages.get(thread, 0.0))
        for kind, total in (stall_counts or {}).items():
            if kind in mc.STALL_KIND_VALUES:
                self._bump_labeled(
                    self.step_stalls, f"stall:{kind}", int(total),
                    {**self._labels, "kind": kind},
                )

    def set_pool_role(self, role: str | None) -> None:
        """Advertise the engine's live pool role (docs/40-pool-rebalancing
        .md): 1 on `role`, 0 on the rest of the closed set; None clears
        both (the engine serves no disaggregated pool)."""
        for value in mc.POOL_ROLE_VALUES:
            self.pool_role.labels(**self._labels, role=value).set(
                1 if value == role else 0
            )

    def _bump(self, counter: Counter, key: str, total: int) -> None:
        self._bump_labeled(counter, key, total, self._labels)

    def _bump_labeled(
        self, counter: Counter, key: str, total: int, labels: dict
    ) -> None:
        prev = self._counter_values.get(key, 0)
        if total > prev:
            counter.labels(**labels).inc(total - prev)
            self._counter_values[key] = total

    def render(
        self, s: EngineStatsSnapshot, openmetrics: bool = False
    ) -> bytes:
        self.update(s)
        if openmetrics:
            return om_exposition.generate_latest(self.registry)
        return generate_latest(self.registry)
